"""DataStream API — the fluent user surface.

Mirrors the shape of the reference's DataStream / KeyedStream /
WindowedStream (SURVEY §2.5: api/datastream/DataStream.java,
KeyedStream.java:227 timeWindow, WindowedStream.java:185 reduce), TPU-adapted:
window aggregations must be declared as associative combines (built-in
sum/min/max/count/mean or jnp-traceable generic reduces) so they execute as
whole-shard kernels; arbitrary per-element Python functions are host-chain
operators fused between keyed boundaries.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax.numpy as jnp
import numpy as np

from flink_tpu.datastream.window.assigners import (
    SessionWindowAssigner,
    SlidingEventTimeWindows,
    TumblingEventTimeWindows,
    WindowAssigner,
)
from flink_tpu.graph import stream_graph as sg
from flink_tpu.ops.window_kernels import ReduceSpec
from flink_tpu.runtime import sinks as sink_mod
from flink_tpu.runtime.watermarks import WatermarkStrategy


def _field_extractor(pos):
    if callable(pos):
        return pos
    if isinstance(pos, (int, str)):
        return lambda e: e[pos]
    raise TypeError(f"cannot extract field {pos!r}")


class DataStream:
    def __init__(self, env, transformation: sg.Transformation):
        self.env = env
        self.transformation = transformation

    # -- stateless chain -------------------------------------------------
    def map(self, fn: Callable) -> "DataStream":
        t = sg.OneInputTransformation("map", self.transformation, kind="map", fn=fn)
        return DataStream(self.env, t)

    def filter(self, fn: Callable) -> "DataStream":
        t = sg.OneInputTransformation("filter", self.transformation, kind="filter", fn=fn)
        return DataStream(self.env, t)

    def flat_map(self, fn: Callable) -> "DataStream":
        t = sg.OneInputTransformation(
            "flat_map", self.transformation, kind="flat_map", fn=fn
        )
        return DataStream(self.env, t)

    def assign_timestamps_and_watermarks(
        self, timestamp_fn: Callable, strategy: Optional[WatermarkStrategy] = None
    ) -> "DataStream":
        t = sg.TimestampsWatermarksTransformation(
            "timestamps", self.transformation,
            timestamp_fn=timestamp_fn,
            strategy=strategy or WatermarkStrategy.for_monotonous_timestamps(),
        )
        return DataStream(self.env, t)

    # -- keying ----------------------------------------------------------
    def key_by(self, selector) -> "KeyedStream":
        t = sg.KeyByTransformation(
            "key_by", self.transformation, key_selector=_field_extractor(selector)
        )
        return KeyedStream(self.env, t)

    # -- multi-stream ----------------------------------------------------
    def union(self, *streams: "DataStream") -> "DataStream":
        """Merge N streams of the same type (ref DataStream.union)."""
        t = sg.UnionTransformation(
            "union",
            parents=[self.transformation] + [s.transformation for s in streams],
        )
        return DataStream(self.env, t)

    def connect(self, other) -> "ConnectedStreams":
        """Two differently-typed streams sharing one operator (ref
        DataStream.connect / ConnectedStreams). Connecting a
        BroadcastStream yields the broadcast state pattern instead."""
        if isinstance(other, BroadcastStream):
            return BroadcastConnectedStream(self.env, self, other)
        return ConnectedStreams(self.env, self, other)

    def join(self, other: "DataStream") -> "JoinedStreams":
        """Windowed equi-join (ref JoinedStreams): per key+window, the cross
        product of both inputs' elements."""
        return JoinedStreams(self.env, self, other, is_cogroup=False)

    def co_group(self, other: "DataStream") -> "JoinedStreams":
        """Windowed coGroup (ref CoGroupedStreams): the user function sees
        both inputs' full element lists per key+window."""
        return JoinedStreams(self.env, self, other, is_cogroup=True)

    def split(self, selector: Callable) -> "SplitStream":
        """Route each element to named outputs (ref SplitStream/
        OutputSelector): selector(element) -> iterable of names."""
        return SplitStream(self.env, self.transformation, selector)

    def iterate(self, max_wait_ms: int = 0) -> "IterativeStream":
        """Streaming iteration head (ref DataStream.iterate): elements loop
        back through the body via close_with(feedback). Terminates when the
        upstream ends and the feedback drains."""
        import collections

        t = sg.IterateTransformation(
            "iterate", self.transformation,
            queue=collections.deque(), max_wait_ms=max_wait_ms,
        )
        return IterativeStream(self.env, t)

    # -- explicit exchange annotations (see PartitionTransformation) -----
    def _partition(self, mode: str) -> "DataStream":
        t = sg.PartitionTransformation(mode, self.transformation, mode=mode)
        return DataStream(self.env, t)

    def broadcast(self, *descriptors) -> "DataStream":
        """Without arguments: the physical-replication annotation (ref
        BroadcastPartitioner.java:30 — on this runtime replicate-and-mask
        already places every record in every shard's address space, so
        the annotation is a no-op declaration). With MapStateDescriptor
        arguments: the broadcast STATE pattern — returns a
        BroadcastStream to connect() against a keyed stream, where every
        parallel instance applies every broadcast element to replicated
        named state (ref KeyedBroadcastProcessFunction)."""
        if descriptors:
            return BroadcastStream(self.env, self.transformation,
                                   descriptors)
        return self._partition("broadcast")

    def rebalance(self) -> "DataStream":
        return self._partition("rebalance")

    def rescale(self) -> "DataStream":
        return self._partition("rescale")

    def shuffle(self) -> "DataStream":
        return self._partition("shuffle")

    def global_(self) -> "DataStream":
        return self._partition("global")

    def forward(self) -> "DataStream":
        return self._partition("forward")

    # -- sinks -----------------------------------------------------------
    def add_sink(self, sink) -> "DataStream":
        if callable(sink) and not isinstance(sink, sink_mod.Sink):
            sink = sink_mod.FunctionSink(sink)
        t = sg.SinkTransformation("sink", self.transformation, sink=sink)
        self.env._sinks.append(t)
        return DataStream(self.env, t)

    def print_(self) -> "DataStream":
        return self.add_sink(sink_mod.PrintSink())

    def write_as_text(self, path: str) -> "DataStream":
        return self.add_sink(sink_mod.WriteAsTextSink(path))


class KeyedStream(DataStream):
    # -- windows ---------------------------------------------------------
    def window(self, assigner) -> "WindowedStream":
        return WindowedStream(self.env, self, assigner)

    def time_window(self, size_ms: int, slide_ms: Optional[int] = None):
        if slide_ms is None:
            return self.window(TumblingEventTimeWindows.of(size_ms))
        return self.window(SlidingEventTimeWindows.of(size_ms, slide_ms))

    def count_window(self, size: int) -> "WindowedStream":
        from flink_tpu.datastream.window.assigners import CountWindowAssigner

        return self.window(CountWindowAssigner(size))

    # -- general keyed processing ----------------------------------------
    def process(self, fn) -> DataStream:
        """Run a ProcessFunction over this keyed stream (ref
        ProcessFunction / StreamTimelyFlatMap): arbitrary host logic with
        keyed heap state + event/processing-time timers. The device kernels
        stay the hot path; this is the generality escape hatch."""
        t = sg.ProcessTransformation("process", self.transformation, fn=fn)
        return DataStream(self.env, t)

    # -- rolling (non-windowed) keyed aggregation ------------------------
    def reduce(self, fn: Callable, extractor=None, neutral=0.0,
               dtype=jnp.float32) -> DataStream:
        """Rolling reduce per key (ref StreamGroupedReduce): emits the
        updated accumulator for every input record."""
        t = sg.KeyedProcessTransformation(
            "rolling_reduce", self.transformation,
            reduce_spec_factory=lambda: ReduceSpec(
                "generic", dtype, combine=fn, neutral=neutral
            ),
            extractor=_field_extractor(extractor) if extractor is not None
            else (lambda e: e),
        )
        return DataStream(self.env, t)

    def sum(self, pos=None) -> DataStream:
        t = sg.KeyedProcessTransformation(
            "rolling_sum", self.transformation,
            reduce_spec_factory=lambda: ReduceSpec("sum", jnp.float32),
            extractor=_field_extractor(pos) if pos is not None else (lambda e: e),
        )
        return DataStream(self.env, t)

    def as_queryable_state(self, name: str, extractor=None,
                           kind: str = "latest") -> DataStream:
        """Expose this keyed stream's latest value (or a running sum) for
        external point lookups under `name` (ref
        KeyedStream.asQueryableState:578 + the KvState server, §2.2).
        Query via env.query_state(name, key), the web monitor's
        /jobs/<jid>/state/<name>?key=..., or QueryableStateClient."""
        if kind == "latest":
            factory = lambda: ReduceSpec(  # noqa: E731
                "generic", jnp.float32, combine=lambda a, b: b, neutral=0.0
            )
        elif kind == "sum":
            factory = lambda: ReduceSpec("sum", jnp.float32)  # noqa: E731
        else:
            raise ValueError(f"unsupported queryable kind {kind!r}")
        t = sg.KeyedProcessTransformation(
            name, self.transformation,
            reduce_spec_factory=factory,
            extractor=_field_extractor(extractor) if extractor is not None
            else (lambda e: e),
        )
        ds = DataStream(self.env, t)
        return ds.add_sink(sink_mod.DiscardingSink())


class IterativeStream(DataStream):
    """Result of DataStream.iterate (ref IterativeStream.closeWith)."""

    def close_with(self, feedback: "DataStream") -> "DataStream":
        q = self.transformation.queue
        t = sg.SinkTransformation(
            "feedback", feedback.transformation,
            sink=sink_mod.QueueSink(q),
        )
        self.env._sinks.append(t)
        return feedback


class SplitStream(DataStream):
    """Result of DataStream.split: select(name) filters by output name."""

    def __init__(self, env, transformation, selector: Callable):
        super().__init__(env, transformation)
        self._selector = selector

    def select(self, *names: str) -> DataStream:
        sel, wanted = self._selector, set(names)
        t = sg.OneInputTransformation(
            f"select({','.join(names)})", self.transformation, kind="filter",
            fn=lambda e: not wanted.isdisjoint(sel(e)),
        )
        return DataStream(self.env, t)


class ConnectedStreams:
    """Two-input streams (ref ConnectedStreams). Lowered as a tagged union
    with per-tag dispatch — structurally what the reference's
    TwoInputStreamTask + CoStreamMap do across two input gates."""

    def __init__(self, env, s1: DataStream, s2: DataStream,
                 key1=None, key2=None):
        self.env = env
        self.s1, self.s2 = s1, s2
        self.key1, self.key2 = key1, key2

    def key_by(self, selector1, selector2) -> "ConnectedStreams":
        return ConnectedStreams(
            self.env, self.s1, self.s2,
            _field_extractor(selector1), _field_extractor(selector2),
        )

    def _union(self) -> sg.UnionTransformation:
        return sg.UnionTransformation(
            "connect",
            parents=[self.s1.transformation, self.s2.transformation],
            tagged=True,
        )

    def map(self, co_map) -> DataStream:
        """co_map: CoMapFunction (map1/map2) or a pair of callables."""
        f1, f2 = (
            (co_map.map1, co_map.map2) if hasattr(co_map, "map1") else co_map
        )
        t = sg.OneInputTransformation(
            "co_map", self._union(), kind="map",
            fn=lambda e: f1(e.value) if e.tag == 0 else f2(e.value),
        )
        return DataStream(self.env, t)

    def flat_map(self, co_flat_map) -> DataStream:
        f1, f2 = (
            (co_flat_map.flat_map1, co_flat_map.flat_map2)
            if hasattr(co_flat_map, "flat_map1") else co_flat_map
        )
        t = sg.OneInputTransformation(
            "co_flat_map", self._union(), kind="flat_map",
            fn=lambda e: f1(e.value) if e.tag == 0 else f2(e.value),
        )
        return DataStream(self.env, t)

    def process(self, co_process) -> DataStream:
        """CoProcessFunction over keyed connected streams: shared keyed
        state + timers across both inputs (requires key_by)."""
        if self.key1 is None or self.key2 is None:
            raise ValueError("connect(...).process requires key_by(k1, k2)")
        k1, k2 = self.key1, self.key2
        keyed = sg.KeyByTransformation(
            "key_by", self._union(),
            key_selector=lambda e: k1(e.value) if e.tag == 0 else k2(e.value),
        )
        t = sg.ProcessTransformation(
            "co_process", keyed, fn=_CoProcessAdapter(co_process)
        )
        return DataStream(self.env, t)


from flink_tpu.datastream.functions import RichFunction as _RichFunction

# broadcast-tagged elements carry no user key; they process under this
# sentinel so the keyed backend's current-key contract holds
_BROADCAST_KEY = "__broadcast__"


class BroadcastStream:
    """A stream declared broadcast with named state descriptors (ref
    BroadcastStream): connect it to a keyed stream and process with a
    KeyedBroadcastProcessFunction."""

    def __init__(self, env, transformation, descriptors):
        self.env = env
        self.transformation = transformation
        self.descriptors = tuple(descriptors)


class BroadcastConnectedStream:
    """Keyed main stream + broadcast control stream (ref
    BroadcastConnectedStream). Lowered as a tagged union re-keyed so
    broadcast elements ride a sentinel key; the adapter below dispatches
    and owns the replicated state."""

    def __init__(self, env, main, bcast: BroadcastStream):
        self.env = env
        self.main = main
        self.bcast = bcast

    def process(self, fn) -> DataStream:
        if not isinstance(self.main, KeyedStream):
            raise ValueError(
                "connect(broadcast_stream) requires a keyed main stream: "
                "call key_by(...) before connect(...)"
            )
        ksel = self.main.transformation.key_selector
        main_parent = self.main.transformation.parent
        union = sg.UnionTransformation(
            "broadcast_connect",
            parents=[main_parent, self.bcast.transformation],
            tagged=True,
        )
        keyed = sg.KeyByTransformation(
            "key_by", union,
            key_selector=lambda e: (
                ksel(e.value) if e.tag == 0 else _BROADCAST_KEY
            ),
        )
        t = sg.ProcessTransformation(
            "broadcast_process", keyed,
            fn=_KeyedBroadcastAdapter(fn, self.bcast.descriptors),
        )
        return DataStream(self.env, t)


class _KeyedBroadcastAdapter(_RichFunction):
    """Dispatches tagged elements to process_element /
    process_broadcast_element and owns the replicated broadcast states.

    State lives in the operator (non-keyed) state store — one dict per
    descriptor boxed as the single item of a named list state — so it
    snapshots into every checkpoint/savepoint and restores with the job
    (ref BroadcastState backed by the operator state backend)."""

    def __init__(self, fn, descriptors):
        self.fn = fn
        self.descriptors = tuple(descriptors)
        self._store = None

    def open(self, ctx):
        self._store = ctx
        if hasattr(self.fn, "open"):
            self.fn.open(ctx)

    def close(self):
        if hasattr(self.fn, "close"):
            self.fn.close()

    def _states(self):
        # re-fetched per call: restore swaps list contents in place, so
        # cached dict references would go stale across a recovery
        out = {}
        for d in self.descriptors:
            ls = self._store.get_operator_list_state(f"broadcast:{d.name}")
            items = ls.get()
            if not items:
                ls.add({})
                items = ls.get()
            out[d.name] = ls._items[0]
        return out

    def process_element(self, e, ctx, out):
        from flink_tpu.datastream.functions import (
            BroadcastProcessContext, ReadOnlyBroadcastContext,
        )

        states = self._states()
        if e.tag == 1:
            self.fn.process_broadcast_element(
                e.value, BroadcastProcessContext(states, ctx), out
            )
        else:
            self.fn.process_element(
                e.value, ReadOnlyBroadcastContext(states, ctx), out
            )

    def on_timer(self, timestamp, ctx, out):
        # timers fired from keyed processing see broadcast state read-only
        # (ref OnTimerContext extends ReadOnlyContext) — proxy the timer
        # ctx and add the accessor
        self.fn.on_timer(
            timestamp, _BroadcastTimerContext(self._states(), ctx), out
        )


class _BroadcastTimerContext:
    """OnTimerContext + read-only broadcast_state access (attribute calls
    delegate to the wrapped timer context)."""

    def __init__(self, states, base):
        self._states = states
        self._base = base

    def __getattr__(self, name):
        return getattr(self._base, name)

    def broadcast_state(self, descriptor_or_name):
        import types

        name = getattr(descriptor_or_name, "name", descriptor_or_name)
        try:
            return types.MappingProxyType(self._states[name])
        except KeyError:
            raise ValueError(
                f"unknown broadcast state {name!r}; declare its "
                f"MapStateDescriptor in stream.broadcast(...)"
            ) from None


class _CoProcessAdapter(_RichFunction):
    """Dispatches Tagged elements to process_element1/2 of a
    CoProcessFunction while presenting the single-input ProcessFunction
    contract to the runtime."""

    def __init__(self, fn):
        self.fn = fn

    def open(self, ctx):
        if hasattr(self.fn, "open"):
            self.fn.open(ctx)

    def close(self):
        if hasattr(self.fn, "close"):
            self.fn.close()

    def process_element(self, e, ctx, out):
        if e.tag == 0:
            self.fn.process_element1(e.value, ctx, out)
        else:
            self.fn.process_element2(e.value, ctx, out)

    def on_timer(self, timestamp, ctx, out):
        self.fn.on_timer(timestamp, ctx, out)


class JoinedStreams:
    """Builder for windowed join/coGroup:
    a.join(b).where(k1).equal_to(k2).window(assigner).apply(fn)

    Lowered exactly as the reference lowers CoGroupedStreams (tagged union →
    keyBy(union selector) → WindowOperator with buffered elements); the join
    variant wraps the coGroup function with the cross-product (ref
    JoinedStreams' JoinCoGroupFunction)."""

    def __init__(self, env, s1, s2, is_cogroup: bool):
        self.env = env
        self.s1, self.s2 = s1, s2
        self.is_cogroup = is_cogroup
        self.k1 = self.k2 = None
        self._assigner = None
        self._lateness_ms = 0

    def where(self, selector) -> "JoinedStreams":
        self.k1 = _field_extractor(selector)
        return self

    def equal_to(self, selector) -> "JoinedStreams":
        self.k2 = _field_extractor(selector)
        return self

    def window(self, assigner) -> "JoinedStreams":
        self._assigner = assigner
        return self

    def time_window(self, size_ms: int, slide_ms: Optional[int] = None):
        if slide_ms is None:
            return self.window(TumblingEventTimeWindows.of(size_ms))
        return self.window(SlidingEventTimeWindows.of(size_ms, slide_ms))

    def allowed_lateness(self, ms: int) -> "JoinedStreams":
        self._lateness_ms = ms
        return self

    def apply(self, fn: Callable) -> DataStream:
        """join: fn(left, right) -> result, per matching pair.
        coGroup: fn(lefts, rights) -> iterable of results."""
        if self.k1 is None or self.k2 is None or self._assigner is None:
            raise ValueError("join requires where/equal_to/window")
        k1, k2 = self.k1, self.k2
        union = sg.UnionTransformation(
            "join_union",
            parents=[self.s1.transformation, self.s2.transformation],
            tagged=True,
        )
        keyed = sg.KeyByTransformation(
            "key_by", union,
            key_selector=lambda e: k1(e.value) if e.tag == 0 else k2(e.value),
        )
        if self.is_cogroup:
            def window_fn(key, window, elements, _fn=fn):
                lefts = [e.value for e in elements if e.tag == 0]
                rights = [e.value for e in elements if e.tag == 1]
                return list(_fn(lefts, rights))
        else:
            def window_fn(key, window, elements, _fn=fn):
                lefts = [e.value for e in elements if e.tag == 0]
                rights = [e.value for e in elements if e.tag == 1]
                return [_fn(x, y) for x in lefts for y in rights]

        t = sg.WindowAggTransformation(
            "join" if not self.is_cogroup else "co_group", keyed,
            assigner=self._assigner,
            extractor=lambda e: e,
            reduce_spec_factory=None,
            allowed_lateness_ms=self._lateness_ms,
            window_fn=window_fn,
        )
        return DataStream(self.env, t)


class WindowedStream:
    def __init__(self, env, keyed: KeyedStream, assigner):
        self.env = env
        self.keyed = keyed
        self.assigner = assigner
        self._lateness_ms = 0
        self._trigger = None
        self._evictor = None

    def allowed_lateness(self, ms: int) -> "WindowedStream":
        self._lateness_ms = ms
        return self

    def trigger(self, trigger) -> "WindowedStream":
        """Attach a custom Trigger (ref WindowedStream.trigger). Routes the
        stage to the generic host window operator."""
        self._trigger = trigger
        return self

    def evictor(self, evictor) -> "WindowedStream":
        """Attach an Evictor (ref WindowedStream.evictor). The window then
        buffers full element lists (EvictingWindowOperator path)."""
        self._evictor = evictor
        return self

    def _agg(self, name, spec_factory, extractor, result_fn=None,
             window_fn=None, value_prep=None) -> DataStream:
        t = sg.WindowAggTransformation(
            name, self.keyed.transformation,
            assigner=self.assigner,
            extractor=extractor,
            reduce_spec_factory=spec_factory,
            result_fn=result_fn,
            value_prep=value_prep,
            allowed_lateness_ms=self._lateness_ms,
            trigger=self._trigger,
            evictor=self._evictor,
            window_fn=window_fn,
        )
        return DataStream(self.env, t)

    def apply(self, window_fn, extractor=None) -> DataStream:
        """General window function over the buffered elements (ref
        WindowedStream.apply:254): window_fn(key, window, elements) ->
        iterable of results. Always runs on the generic host operator."""
        return self._agg(
            "window_apply", None,
            _field_extractor(extractor) if extractor is not None
            else (lambda e: e),
            window_fn=window_fn,
        )

    def fold(self, initial, fold_fn, extractor=None) -> DataStream:
        """Non-associative fold over the window's elements in arrival order
        (ref WindowedStream.fold:213)."""
        def fn(key, window, elements, _init=initial, _fold=fold_fn):
            acc = _init
            for v in elements:
                acc = _fold(acc, v)
            return [acc]

        return self._agg(
            "window_fold", None,
            _field_extractor(extractor) if extractor is not None
            else (lambda e: e),
            window_fn=fn,
        )

    def sum(self, pos=None, dtype=jnp.float32) -> DataStream:
        return self._agg(
            "window_sum",
            lambda: ReduceSpec("sum", dtype),
            _field_extractor(pos) if pos is not None else (lambda e: e),
        )

    def min(self, pos=None, dtype=jnp.float32) -> DataStream:
        return self._agg(
            "window_min", lambda: ReduceSpec("min", dtype),
            _field_extractor(pos) if pos is not None else (lambda e: e),
        )

    def max(self, pos=None, dtype=jnp.float32) -> DataStream:
        return self._agg(
            "window_max", lambda: ReduceSpec("max", dtype),
            _field_extractor(pos) if pos is not None else (lambda e: e),
        )

    def count(self) -> DataStream:
        def ones(e):
            # columnar batches need a per-lane column; scalar per element
            if isinstance(e, dict):
                import numpy as _np

                n = len(next(iter(e.values())))
                return _np.ones(n, _np.float32)
            return 1.0

        return self._agg(
            "window_count", lambda: ReduceSpec("count", jnp.float32), ones,
        )

    def mean(self, pos=None) -> DataStream:
        """sum+count composite accumulator, host-side divide at fire."""
        def extractor(e):
            v = _field_extractor(pos)(e) if pos is not None else e
            return np.asarray([v, 1.0], np.float32)

        return self._agg(
            "window_mean",
            lambda: ReduceSpec("sum", jnp.float32, value_shape=(2,)),
            extractor,
            result_fn=lambda acc: acc[..., 0] / np.maximum(acc[..., 1], 1.0),
        )

    def reduce(self, fn: Callable, extractor=None, neutral=0.0,
               dtype=jnp.float32, value_shape=()) -> DataStream:
        """General associative reduce. fn must be jnp-traceable; for
        arbitrary element types provide extractor (element -> array) and
        result_fn via .aggregate()."""
        return self._agg(
            "window_reduce",
            lambda: ReduceSpec("generic", dtype, value_shape,
                               combine=fn, neutral=neutral),
            _field_extractor(extractor) if extractor is not None else (lambda e: e),
        )

    def distinct_count(self, pos=None, precision: int = 12) -> DataStream:
        """Approximate per-key distinct count of the extracted item per
        window via a HyperLogLog register array in device state (BASELINE
        config #3). Emits a float estimate per key per window."""
        from flink_tpu.ops import sketches as sk

        def factory(p=precision):
            h = sk.HyperLogLog(p)
            return ReduceSpec(
                "sketch", h.dtype, h.value_shape, sketch=h,
                finalize=h.finalize, result_shape=h.result_shape,
                result_dtype=h.result_dtype,
            )

        return self._agg(
            "window_hll",
            factory,
            _field_extractor(pos) if pos is not None else (lambda e: e),
            value_prep=sk.hash32_host,
        )

    def count_min(self, pos=None, depth: int = 4, width: int = 1024,
                  query=None) -> DataStream:
        """Per-key Count-Min sketch of the extracted items per window
        (BASELINE config #3). With `query` (a fixed item list) each fire
        emits the Q point estimates; otherwise the raw depth*width register
        vector (queryable via CountMinSketch.estimate_np)."""
        from flink_tpu.ops import sketches as sk

        def factory(d=depth, w=width, q=query):
            cms = sk.CountMinSketch(d, w, query=q)
            kwargs = dict(sketch=cms)
            if q is not None:
                kwargs.update(finalize=cms.finalize,
                              result_shape=cms.result_shape,
                              result_dtype=cms.result_dtype)
            return ReduceSpec("sketch", cms.dtype, cms.value_shape, **kwargs)

        return self._agg(
            "window_cms",
            factory,
            _field_extractor(pos) if pos is not None else (lambda e: e),
            value_prep=sk.hash32_host,
        )

    def aggregate(self, agg_fn) -> DataStream:
        """AggregateFunction contract (add/merge/get_result) — ref
        AggregatingState. agg_fn: state.AggregatingStateDescriptor or any
        object with .to_reduce_spec(), .extractor, .get_result."""
        return self._agg(
            "window_aggregate",
            agg_fn.to_reduce_spec,
            getattr(agg_fn, "extractor", lambda e: e),
            result_fn=getattr(agg_fn, "get_result", None),
        )
