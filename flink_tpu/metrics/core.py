"""Metric types + hierarchical groups + registry/reporter SPI.

Mirrors flink-metrics-core (SURVEY §5 Metrics): Counter / Gauge /
Histogram / Meter, hierarchical MetricGroups (job → task → operator, ref
TaskMetricGroup/OperatorMetricGroup scope chain), and a MetricRegistry
fanning out to pluggable reporters (ref MetricRegistry.java:51 + the
flink-metrics-* reporter modules). Reporters here are pull-based: the
registry snapshots on demand (the metric-query-service role) and
ScheduledReporter drives periodic pushes.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class Counter:
    def __init__(self):
        self._v = 0

    def inc(self, n: int = 1):
        self._v += n

    def dec(self, n: int = 1):
        self._v -= n

    def get_count(self) -> int:
        return self._v


class Gauge:
    def __init__(self, fn: Callable[[], Any]):
        self._fn = fn

    def get_value(self):
        return self._fn()


class SettableGauge(Gauge):
    """Gauge holding a pushed value instead of polling a closure — for
    producers that know the value only at irregular events (e.g. the
    key-group coverage of the last incremental checkpoint), where a
    polled closure would have to reach into producer internals."""

    def __init__(self, initial: Any = None):
        super().__init__(lambda: self._v)
        self._v = initial

    def set(self, value: Any):
        self._v = value


class Histogram:
    """Sliding-window histogram with percentile snapshots (ref
    DescriptiveStatisticsHistogram role). Updates come from the job thread
    while web/reporter threads read — a lock keeps the copy consistent."""

    def __init__(self, window: int = 1024):
        self._values = deque(maxlen=window)
        self._lock = threading.Lock()

    def update(self, v: float):
        with self._lock:
            self._values.append(float(v))

    def _copy(self) -> List[float]:
        with self._lock:
            return list(self._values)

    def get_count(self) -> int:
        return len(self._values)

    @staticmethod
    def _q(vs: List[float], q: float) -> float:
        idx = min(len(vs) - 1, max(0, math.ceil(q * len(vs)) - 1))
        return vs[idx]

    def quantile(self, q: float) -> float:
        vs = sorted(self._copy())
        if not vs:
            return float("nan")
        return self._q(vs, q)

    def snapshot(self) -> Dict[str, float]:
        vs = sorted(self._copy())
        if not vs:
            return {"count": 0}
        return {
            "count": len(vs),
            "min": vs[0],
            "max": vs[-1],
            "mean": sum(vs) / len(vs),
            "p50": self._q(vs, 0.50),
            "p95": self._q(vs, 0.95),
            "p99": self._q(vs, 0.99),
        }


class Meter:
    """Events-per-second over a sliding interval (ref MeterView)."""

    def __init__(self, interval_s: float = 60.0):
        self.interval_s = interval_s
        self._events = deque()
        self._count = 0
        self._lock = threading.Lock()  # job thread writes, web/reporter read

    def mark_event(self, n: int = 1):
        now = time.monotonic()
        with self._lock:
            self._events.append((now, n))
            self._count += n
            self._evict(now)

    def _evict(self, now):
        while self._events and self._events[0][0] < now - self.interval_s:
            self._events.popleft()

    def get_rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._evict(now)
            total = sum(n for _, n in self._events)
            first = self._events[0][0] if self._events else None
        # clamp the span so a read right after the first event reports
        # <= total events/sec instead of an absurd instantaneous rate
        span = max(1.0, now - first if first is not None else self.interval_s)
        return total / span

    def get_count(self) -> int:
        return self._count


class MetricGroup:
    """Hierarchical scope (ref AbstractMetricGroup): metrics register into
    the root registry with a dotted scope identifier."""

    def __init__(self, registry: "MetricRegistry", scope: List[str]):
        self._registry = registry
        self._scope = scope

    def add_group(self, name: str) -> "MetricGroup":
        return MetricGroup(self._registry, self._scope + [str(name)])

    def scope_string(self, name: str = "") -> str:
        return ".".join(self._scope + ([name] if name else []))

    def _register(self, name: str, metric):
        self._registry.register(self.scope_string(name), metric)
        return metric

    def counter(self, name: str) -> Counter:
        return self._register(name, Counter())

    def settable_gauge(self, name: str, initial: Any = None) -> SettableGauge:
        return self._register(name, SettableGauge(initial))

    def gauge(self, name: str, fn: Callable[[], Any]) -> Gauge:
        return self._register(name, Gauge(fn))

    def remove(self, name: str):
        """Drop a metric registered under this group's scope — the
        scale-DOWN half of idempotent re-registration: per-shard series
        re-registered on an elastic re-plan overwrite in place, but the
        shards that no longer exist must be unregistered or their stale
        gauges keep reporting the dead mesh forever."""
        self._registry.unregister(self.scope_string(name))

    def histogram(self, name: str, window: int = 1024) -> Histogram:
        return self._register(name, Histogram(window))

    def meter(self, name: str, interval_s: float = 60.0) -> Meter:
        return self._register(name, Meter(interval_s))


class MetricRegistry:
    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._reporters: List["Reporter"] = []
        self._lock = threading.Lock()

    def register(self, scope: str, metric):
        with self._lock:
            self._metrics[scope] = metric
        for r in self._reporters:
            r.notify_added(scope, metric)

    def unregister(self, scope: str):
        with self._lock:
            self._metrics.pop(scope, None)

    def add_reporter(self, reporter: "Reporter"):
        self._reporters.append(reporter)
        reporter.open(self)

    def close(self):
        for r in self._reporters:
            r.close()

    def group(self, *scope: str) -> MetricGroup:
        return MetricGroup(self, list(scope))

    def items(self, prefix: str = "") -> List:
        """[(scope, metric)] — the TYPED view reporters that distinguish
        counters from gauges need (snapshot() collapses to values)."""
        with self._lock:
            return [
                (k, m) for k, m in self._metrics.items()
                if k.startswith(prefix)
            ]

    def snapshot(self, prefix: str = "") -> Dict[str, Any]:
        """Point-in-time values of every registered metric (the metric
        query service consumed by the web monitor, ref MetricDump)."""
        with self._lock:
            items = [
                (k, m) for k, m in self._metrics.items()
                if k.startswith(prefix)
            ]
        out = {}
        for k, m in items:
            if isinstance(m, Counter):
                out[k] = m.get_count()
            elif isinstance(m, Gauge):
                try:
                    out[k] = m.get_value()
                except Exception as e:  # a broken gauge must not kill reports
                    out[k] = f"<error: {e}>"
            elif isinstance(m, Histogram):
                out[k] = m.snapshot()
            elif isinstance(m, Meter):
                out[k] = {"rate": m.get_rate(), "count": m.get_count()}
            else:
                out[k] = repr(m)
        return out


class Reporter:
    """Reporter SPI (ref MetricReporter)."""

    def open(self, registry: MetricRegistry):
        self.registry = registry

    def notify_added(self, scope: str, metric):
        pass

    def report(self):
        pass

    def close(self):
        pass


class JsonFileReporter(Reporter):
    """Dumps the full snapshot as one JSON object per report() call."""

    def __init__(self, path: str):
        self.path = path

    def report(self):
        with open(self.path, "w") as f:
            json.dump(self.registry.snapshot(), f, indent=2, default=str)


class LoggingReporter(Reporter):
    def __init__(self, log_fn: Callable[[str], None] = print):
        self.log_fn = log_fn

    def report(self):
        for k, v in sorted(self.registry.snapshot().items()):
            self.log_fn(f"{k} = {v}")


class ScheduledReporter(threading.Thread):
    """Drives reporter.report() every interval (ref the registry's reporter
    scheduling executor)."""

    def __init__(self, reporter: Reporter, interval_s: float):
        super().__init__(daemon=True)
        self.reporter = reporter
        self.interval_s = interval_s
        self._stop = threading.Event()

    def run(self):
        import logging

        log = logging.getLogger(__name__)
        while not self._stop.wait(self.interval_s):
            try:
                self.reporter.report()
            except Exception:
                # a transient failure must not kill future reports, but a
                # permanent one must be visible
                log.warning("metric reporter failed", exc_info=True)

    def stop(self):
        self._stop.set()
