"""Drain-interior flight recorder, host side (ISSUE 14).

Rounds 12-13 made steady state a count-gated ring drain: one dispatch
retires up to ``ring-depth`` staged batches per shard, so the span
tracer's ``drain`` span and the cycle attribution both see D x n_shards
slots of real work as a single opaque interval. The device half of this
round (runtime/step.py ``DRAIN_STAT_FIELDS`` payload, statically gated
by ``observability.drain-stats``) stacks per-slot x per-shard counters
inside the drain scan; this module is the host half that turns the
lagged payload plus the rings' publish-time stamps into:

  * per-shard ring occupancy / backpressure time series (fill sampled
    at publish and at drain, joined with the publish-refusal counters);
  * a drain duty-cycle estimator — device-busy vs ring-starved EWMA per
    shard, feeding the resident-aware ``CycleAttribution`` regimes;
  * event-time-to-fire and publish-seq-to-consume latency flowing into
    ``LatencySamples`` weighted percentiles;
  * Perfetto counter tracks (``SpanTracer.rec_counter``) so the series
    render as stacked lanes above the phase spans.

Threading: the executor's step loop calls the ``ingest_publish`` /
``on_drain`` / ``note_fires`` mutators; web and reporter threads read
``report()`` and the gauge accessors. One lock guards the tiny mutable
core (deque appends and EWMA floats — nanosecond critical sections).

This module is on the hot-path-sync lint list (tools/lint/rules/
hot_path_sync.py): everything here must stay pure host arithmetic over
ALREADY-FETCHED numpy payloads — the lagged consume path stays sync-
free, and any ``jax.device_get``/``np.asarray`` creeping in fails lint.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.metrics.latency import LatencySamples

# Per-slot counter layout emitted by the drain scan body — the single
# source of truth; runtime/step.py imports it so the kernel packer and
# this unpacker cannot drift.
DRAIN_STAT_FIELDS = (
    "events",          # records retired from the slot (valid lanes)
    "activity",        # table placements (insert) / probe misses (fast)
    "fire_lanes",      # fire lanes packed for the slot's pane crossings
    "fired_keys",      # sum of per-lane fired key counts
    "late_dropped",    # lanes dropped late (allowed-lateness breach)
    "nofit_dropped",   # lanes dropped for capacity (no fit after probe)
    "ovf_fill",        # overflow-ring fill after the slot retired
    "kg_fill_max",     # max per-key-group fill (skew summary)
    "panes_advanced",  # panes the slot's watermark advance crossed
)

# monotonically accumulating fields vs instantaneous levels: totals are
# summed for the former, the latest fetch's max-over-slots is reported
# for the latter (summing a fill level across slots is meaningless)
COUNTER_FIELDS = ("events", "activity", "fire_lanes", "fired_keys",
                  "late_dropped", "nofit_dropped", "panes_advanced")
LEVEL_FIELDS = ("ovf_fill", "kg_fill_max")

# Per-downstream-stage record emitted ONCE per drain by the chained
# stage tail (ISSUE 17) — one row per stage j >= 1, stacked to
# ``[n_stages-1, n_shards, len(STAGE_STAT_FIELDS)]`` next to the
# stage-0 per-slot payload. Single source of truth: runtime/step.py
# packs by this order, this module unpacks by it.
STAGE_STAT_FIELDS = (
    "edge_demand",       # upstream fire lanes offered to the edge
                         # (pre-clamp: demand > exchange-lanes budget
                         # means the edge dropped)
    "edge_events",       # lanes actually inserted (min(demand, E))
    "fire_lanes",        # downstream fire lanes packed this drain
    "dropped_capacity",  # edge lanes dropped for lane-budget overflow
    "wm_lag_panes",      # coupled-watermark lag behind upstream, in
                         # downstream pane widths (level)
    "panes_advanced",    # downstream panes this drain's advance crossed
)
STAGE_COUNTER_FIELDS = ("edge_demand", "edge_events", "fire_lanes",
                        "dropped_capacity", "panes_advanced")
STAGE_LEVEL_FIELDS = ("wm_lag_panes",)


class DrainTelemetry:
    """Aggregates the drain flight-recorder payload into per-shard
    series, duty-cycle EWMAs, and latency percentiles."""

    def __init__(self, n_shards: int, ring_depth: int,
                 alpha: float = 0.1, max_series: int = 512,
                 tracer=None, n_stages: int = 1,
                 exchange_lanes: int = 0, key_groups: int = 0,
                 kg_alpha: float = 0.05):
        self.n_shards = max(1, int(n_shards))
        self.ring_depth = max(1, int(ring_depth))
        self.alpha = float(alpha)
        self.tracer = tracer
        self.t0 = time.perf_counter()
        n = self.n_shards
        nf = len(DRAIN_STAT_FIELDS)
        self._totals = np.zeros((n, nf), np.int64)
        self._last = np.zeros((n, nf), np.int64)
        # stage-aware half (chained drains): per-downstream-stage
        # counter totals / latest levels / per-drain peaks, summed
        # (resp. maxed) over shards at absorb time
        self.n_stages = max(1, int(n_stages))
        self.exchange_lanes = max(0, int(exchange_lanes))
        nsf = len(STAGE_STAT_FIELDS)
        self._stage_totals = np.zeros((self.n_stages - 1, nsf), np.int64)
        self._stage_last = np.zeros((self.n_stages - 1, nsf), np.int64)
        self._stage_peak = np.zeros((self.n_stages - 1, nsf), np.int64)
        # key-group heat: EWMA of sampled per-batch fill plus a
        # last-touched recency counter, per key group
        self.key_groups = max(0, int(key_groups))
        self.kg_alpha = float(kg_alpha)
        self._kg_heat = np.zeros(self.key_groups, np.float64)
        self._kg_last = np.full(self.key_groups, -1, np.int64)
        self._kg_seq = 0
        self._duty = [0.0] * n          # device-busy EWMA (count/depth)
        self._starved = [0.0] * n       # empty-ring drain EWMA
        self._fill = [0] * n            # last observed ring fill
        self._drains = 0                # drain dispatches seen
        self._fetches = 0               # payload fetches unpacked
        # per-shard occupancy series: (t_rel_s, fill, source)
        self._occ: List[deque] = [
            deque(maxlen=max(16, int(max_series))) for _ in range(n)
        ]
        # per-shard outstanding publishes awaiting release: (seq, t)
        self._pending: List[deque] = [
            deque(maxlen=4096) for _ in range(n)
        ]
        # event-tick -> publish-wall lookup for fire latency; ticks and
        # times both ascend so bisect over a parallel pair of lists
        self._tick: List[int] = []
        self._tick_t: List[float] = []
        self._fire_lat = LatencySamples()
        self._consume_lat = LatencySamples()
        self._lock = threading.Lock()

    # -- mutators (step loop) --------------------------------------------

    def ingest_publish(self, samples: Sequence[Tuple]):
        """Absorb publish-time stamps drained from a batch ring:
        ``(shard, seq_or_None, fill_after, max_tick_or_None, t_wall)``
        tuples appended inside the ring's locked commit section."""
        with self._lock:
            for shard, seq, fill, max_tick, t in samples:
                s = int(shard)
                if not 0 <= s < self.n_shards:
                    continue
                self._fill[s] = int(fill)
                self._occ[s].append((t - self.t0, int(fill), "publish"))
                if seq is not None:
                    self._pending[s].append((int(seq), t))
                if max_tick is not None and (
                        not self._tick or int(max_tick) > self._tick[-1]):
                    self._tick.append(int(max_tick))
                    self._tick_t.append(t)
                    if len(self._tick) > 8192:
                        del self._tick[:4096]
                        del self._tick_t[:4096]

    def on_drain(self, counts: Sequence[int],
                 fills: Sequence[int],
                 released: Sequence[Optional[int]],
                 t_wall: Optional[float] = None):
        """One drain dispatch retired: ``counts[s]`` slots drained from
        shard ``s``'s ring, ``fills[s]`` the lane fill after release,
        ``released[s]`` the released-through seq (None: nothing ringed).
        Updates the duty/starved EWMAs, occupancy series and publish-to-
        consume latency — called every drain regardless of the payload
        fetch cadence (``absorb_payload`` handles the sampled half)."""
        if t_wall is None:
            t_wall = time.perf_counter()
        a = self.alpha
        with self._lock:
            self._drains += 1
            tracks = []
            for s in range(self.n_shards):
                cnt = int(counts[s]) if s < len(counts) else 0
                fill = int(fills[s]) if s < len(fills) else 0
                duty = min(1.0, cnt / self.ring_depth)
                # a shallow drain that leaves the lane EMPTY means the
                # publish side cannot keep the ring fed (ring-starved);
                # full-depth drains are the device-saturated signature
                starved = (
                    1.0 if (fill == 0 and cnt < self.ring_depth) else 0.0
                )
                self._duty[s] += a * (duty - self._duty[s])
                self._starved[s] += a * (starved - self._starved[s])
                self._fill[s] = fill
                self._occ[s].append((t_wall - self.t0, fill, "drain"))
                rel = released[s] if s < len(released) else None
                if rel is not None:
                    q = self._pending[s]
                    while q and q[0][0] <= int(rel):
                        _seq, t_pub = q.popleft()
                        self._consume_lat.record(
                            1, (t_wall - t_pub) * 1e3
                        )
                tracks.append((f"drain/shard{s}", {
                    "fill": fill,
                    "duty_pct": round(self._duty[s] * 100.0, 1),
                }))
            tr = self.tracer
        if tr is not None and tr.active:
            for track, values in tracks:
                tr.rec_counter(track, t_wall, **values)

    def absorb_payload(self, ds: np.ndarray,
                       t_wall: Optional[float] = None):
        """Fold one fetched ``[n_shards, D, len(FIELDS)]`` flight-
        recorder payload (already host-resident — the lagged consume
        path fetched it batched with the fire payload) into the totals
        and level views, and emit per-shard counter-track samples."""
        if t_wall is None:
            t_wall = time.perf_counter()
        per_shard = ds.sum(axis=1, dtype=np.int64)
        last = ds.max(axis=1).astype(np.int64)
        if per_shard.shape[0] != self.n_shards:
            # global-ring resident mode on a multi-shard mesh: the
            # payload still carries one row per mesh shard, but the
            # ring (and so this aggregator) has a single lane — fold
            per_shard = per_shard.sum(axis=0, keepdims=True)
            last = last.max(axis=0, keepdims=True)
        with self._lock:
            self._fetches += 1
            self._totals += per_shard
            self._last = last
            tr = self.tracer
        if tr is not None and tr.active:
            for s in range(per_shard.shape[0]):
                tr.rec_counter(
                    f"drain_retired/shard{s}", t_wall,
                    events=int(per_shard[s][0]),
                    fire_lanes=int(per_shard[s][2]),
                )

    def absorb_stage_payload(self, ss: np.ndarray,
                             t_wall: Optional[float] = None):
        """Fold one fetched ``[n_stages-1, n_shards, len(STAGE_STAT_
        FIELDS)]`` per-downstream-stage record (the chained tail emits
        ONE row per stage per drain) into stage totals, latest levels
        and per-drain peaks, and emit per-stage counter tracks."""
        if t_wall is None:
            t_wall = time.perf_counter()
        ss = ss.astype(np.int64, copy=False)
        if ss.ndim == 2:            # single-shard payload without axis
            ss = ss[:, None, :]
        n_down = min(ss.shape[0], self.n_stages - 1)
        if n_down <= 0:
            return
        per_stage = ss[:n_down].sum(axis=1)          # counters: + shards
        lvl = ss[:n_down].max(axis=1)                # levels: max shard
        with self._lock:
            self._stage_totals[:n_down] += per_stage
            self._stage_last[:n_down] = lvl
            self._stage_peak[:n_down] = np.maximum(
                self._stage_peak[:n_down], lvl
            )
            tr = self.tracer
        if tr is not None and tr.active:
            fi = {f: i for i, f in enumerate(STAGE_STAT_FIELDS)}
            for j in range(n_down):
                tr.rec_counter(
                    f"drain_stage{j + 1}", t_wall,
                    edge_lanes=int(lvl[j][fi["edge_events"]]),
                    fire_lanes=int(lvl[j][fi["fire_lanes"]]),
                    wm_lag_panes=int(lvl[j][fi["wm_lag_panes"]]),
                )

    def absorb_kg_fill(self, counts: np.ndarray, n_batches: int = 1):
        """Fold one sampled per-key-group fill vector (the lagged
        monitoring fetch the executor already performs) into the heat
        EWMA + last-touched recency — the demote/prefetch and
        live-rebalance sensor. Pure host numpy on an already-fetched
        array."""
        counts = counts.astype(np.float64, copy=False).ravel()
        if counts.size == 0:
            return
        obs = counts / max(1, int(n_batches))
        a = self.kg_alpha
        with self._lock:
            if counts.size != self.key_groups:
                self.key_groups = counts.size
                heat = np.zeros(counts.size, np.float64)
                last = np.full(counts.size, -1, np.int64)
                n = min(self._kg_heat.size, counts.size)
                heat[:n] = self._kg_heat[:n]
                last[:n] = self._kg_last[:n]
                self._kg_heat, self._kg_last = heat, last
            self._kg_seq += 1
            self._kg_heat += a * (obs - self._kg_heat)
            self._kg_last[counts > 0] = self._kg_seq

    def note_fires(self, pairs: Sequence[Tuple[int, int]],
                   t_wall: Optional[float] = None):
        """Record event-time-to-fire latency for an emission:
        ``(window_end_tick, n_windows)`` pairs. The latency of a window
        is measured from the first publish whose max event tick crossed
        its end (the moment the fire became due on the device) to now —
        pure wall time, no tick-to-ms conversion needed."""
        if t_wall is None:
            t_wall = time.perf_counter()
        with self._lock:
            for wend, n in pairs:
                i = bisect_left(self._tick, int(wend))
                if i < len(self._tick_t) and n > 0:
                    self._fire_lat.record(
                        int(n), (t_wall - self._tick_t[i]) * 1e3
                    )

    # -- readers (web / reporter threads) --------------------------------

    def duty_cycle(self, s: int) -> float:
        with self._lock:
            return self._duty[s] if 0 <= s < self.n_shards else 0.0

    def slot_fill(self, s: int) -> int:
        with self._lock:
            return self._fill[s] if 0 <= s < self.n_shards else 0

    def fire_latency_ms(self, q: float) -> Optional[float]:
        with self._lock:
            return self._fire_lat.percentile(q)

    def consume_latency_ms(self, q: float) -> Optional[float]:
        with self._lock:
            return self._consume_lat.percentile(q)

    def stage_stat(self, stage: int, field: str) -> int:
        """Latest-level (LEVEL fields) or running-total (COUNTER
        fields) value for downstream stage ``stage`` (1-based)."""
        j = int(stage) - 1
        if not 0 <= j < self.n_stages - 1 or field not in STAGE_STAT_FIELDS:
            return 0
        i = STAGE_STAT_FIELDS.index(field)
        with self._lock:
            src = (self._stage_last if field in STAGE_LEVEL_FIELDS
                   else self._stage_totals)
            return int(src[j][i])

    def kg_heat_block(self, k: int = 8) -> Dict[str, Any]:
        """Top-k/cold-tail view of the key-group heat series."""
        with self._lock:
            heat = self._kg_heat.copy()
            last = self._kg_last.copy()
            seq = self._kg_seq
            alpha = self.kg_alpha
        if heat.size == 0 or seq == 0:
            return {"available": False, "samples": seq,
                    "hint": "needs observability.kg-stats and traffic"}
        order = np.argsort(heat)[::-1][:max(1, int(k))]
        touched = last >= 0
        mean_heat = float(heat[touched].mean()) if touched.any() else 0.0
        max_heat = float(heat.max())
        # cold tail: groups never touched, or whose heat decayed below
        # 10% of the mean over touched groups — the demote candidates
        cold = (~touched) | (heat < 0.1 * mean_heat)
        return {
            "available": True,
            "alpha": alpha,
            "samples": seq,
            "groups": int(heat.size),
            "skew_ratio": round(max_heat / mean_heat, 4)
            if mean_heat > 0 else 0.0,
            "top": [
                {
                    "group": int(g),
                    "heat": round(float(heat[g]), 4),
                    "last_touched_ago": (
                        int(seq - last[g]) if last[g] >= 0 else None
                    ),
                }
                for g in order if heat[g] > 0
            ],
            "cold_tail": {
                "count": int(cold.sum()),
                "fraction": round(float(cold.mean()), 4),
            },
        }

    def kg_heat_max(self) -> float:
        with self._lock:
            return float(self._kg_heat.max()) if self._kg_heat.size else 0.0

    def kg_heat_skew(self) -> float:
        with self._lock:
            heat = self._kg_heat
            touched = self._kg_last >= 0
            if not touched.any():
                return 0.0
            mean = float(heat[touched].mean())
            return float(heat.max()) / mean if mean > 0 else 0.0

    def regime(self) -> Tuple[float, float]:
        """(mean duty-cycle, mean ring-starved fraction) across shards —
        the resident-loop signal ``CycleAttribution`` classifies on."""
        with self._lock:
            n = self.n_shards
            return (sum(self._duty) / n, sum(self._starved) / n)

    def report(self, refusals: Optional[Sequence[int]] = None,
               occupancy_points: int = 64) -> Dict[str, Any]:
        """The /jobs/<jid>/pipeline payload body."""
        with self._lock:
            shards = []
            for s in range(self.n_shards):
                occ = list(self._occ[s])[-occupancy_points:]
                row: Dict[str, Any] = {
                    "shard": s,
                    "duty_cycle": round(self._duty[s], 4),
                    "ring_starved": round(self._starved[s], 4),
                    "slot_fill": self._fill[s],
                    "occupancy": [
                        [round(t, 4), fill, src] for t, fill, src in occ
                    ],
                    "totals": {
                        f: int(self._totals[s][i])
                        for i, f in enumerate(DRAIN_STAT_FIELDS)
                        if f in COUNTER_FIELDS
                    },
                    "levels": {
                        f: int(self._last[s][i])
                        for i, f in enumerate(DRAIN_STAT_FIELDS)
                        if f in LEVEL_FIELDS
                    },
                }
                if refusals is not None and s < len(refusals):
                    row["publish_refusals"] = int(refusals[s])
                shards.append(row)

            def pct(lat: LatencySamples) -> Dict[str, Any]:
                out: Dict[str, Any] = {"samples": len(lat)}
                for q in (50.0, 95.0, 99.0):
                    v = lat.percentile(q)
                    out[f"p{int(q)}"] = (
                        round(v, 3) if v is not None else None
                    )
                return out

            out: Dict[str, Any] = {
                "available": True,
                "n_shards": self.n_shards,
                "ring_depth": self.ring_depth,
                "drains": self._drains,
                "payload_fetches": self._fetches,
                "fields": list(DRAIN_STAT_FIELDS),
                "shards": shards,
                "latency_ms": {
                    "event_to_fire": pct(self._fire_lat),
                    "publish_to_consume": pct(self._consume_lat),
                },
            }
            if self.n_stages > 1:
                fi = {f: i for i, f in enumerate(STAGE_STAT_FIELDS)}
                budget = self.exchange_lanes
                stages = []
                for j in range(self.n_stages - 1):
                    peak_demand = int(
                        self._stage_peak[j][fi["edge_demand"]]
                    )
                    stages.append({
                        "stage": j + 1,
                        "totals": {
                            f: int(self._stage_totals[j][fi[f]])
                            for f in STAGE_COUNTER_FIELDS
                        },
                        "levels": {
                            f: int(self._stage_last[j][fi[f]])
                            for f in STAGE_LEVEL_FIELDS
                        },
                        "edge_lane_budget": budget,
                        "edge_peak_demand": peak_demand,
                        "edge_utilization": (
                            round(peak_demand / budget, 4)
                            if budget > 0 else None
                        ),
                    })
                out["stages"] = stages
                out["stage_fields"] = list(STAGE_STAT_FIELDS)
        if self.key_groups > 0:
            out["kg_heat"] = self.kg_heat_block()
        return out
