"""Weighted latency sampling shared by JobMetrics and bench harnesses.

One emission of N windows at latency L contributes the weighted sample
(N, L); percentiles are computed over windows, not over emissions (the
reference's latency histograms are likewise per-element, LatencyMarker /
DescriptiveStatisticsHistogram). The sample list is bounded: past
``max_samples`` it compacts by merging adjacent sorted pairs, which
preserves the weighted distribution to well under bucket resolution while
keeping memory O(1) for perpetual streaming jobs.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def weighted_percentile(samples: List[Tuple[float, float]],
                        q: float) -> Optional[float]:
    """Percentile (0..100) over weighted (weight, value) samples."""
    if not samples:
        return None
    val = np.asarray([v for _, v in samples], dtype=np.float64)
    w = np.asarray([n for n, _ in samples], dtype=np.float64)
    order = np.argsort(val)
    val, w = val[order], w[order]
    cdf = np.cumsum(w) / w.sum()
    idx = min(int(np.searchsorted(cdf, q / 100.0)), len(val) - 1)
    return float(val[idx])


class LatencySamples:
    """Bounded weighted (n, ms) sample sink with percentile queries."""

    def __init__(self, max_samples: int = 32768):
        self.max_samples = max_samples
        self._samples: List[Tuple[float, float]] = []

    def record(self, n: int, ms: float):
        if n:
            self._samples.append((float(n), float(ms)))
            if len(self._samples) > self.max_samples:
                self._compact()

    def _compact(self):
        """Halve by merging adjacent sorted pairs (weight-sum, weighted
        mean) — distribution-preserving at this resolution."""
        s = sorted(self._samples, key=lambda t: t[1])
        out = []
        for i in range(0, len(s) - 1, 2):
            (n1, v1), (n2, v2) = s[i], s[i + 1]
            n = n1 + n2
            out.append((n, (n1 * v1 + n2 * v2) / n))
        if len(s) % 2:
            out.append(s[-1])
        self._samples = out

    def percentile(self, q: float) -> Optional[float]:
        return weighted_percentile(self._samples, q)

    def __len__(self):
        return len(self._samples)

    def __bool__(self):
        return bool(self._samples)
