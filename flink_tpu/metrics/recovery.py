"""MTTR instrumentation: per-attempt recovery phase breakdowns.

PR 4 made failures contained and *attributed*; this module makes the
recovery that follows them *measured*. At production scale failures are
continuous background noise, so detect-to-first-fire — how long the
stream is dark after a crash — is the availability number (the Hazelcast
Jet argument: a production engine is judged on its tail behavior under
disturbance, not its steady-state throughput). Every restart attempt
records one row:

    detect      failure raise -> recovery entered (async settle excluded)
    settle      pending async checkpoint cuts becoming durable/cancelled
    backoff     restart-strategy delay (fixed / exponential-backoff)
    restore_plan  producer pause, in-flight invalidation, manifest/chain
                resolution — everything before bytes move
    fetch       checkpoint blobs -> host entries (local cache or primary;
                the tier split shows up in the cache hit/miss counters)
    stage       host entries -> device state (full rebuild, or the warm
                path's dirty-shard splice)
    compile     XLA compile wall-time between recovery entry and the
                first post-restore fire (0 on the warm path — reusing
                the live jitted kernels is the point)
    reslice     elastic re-plan only: survivor planning + mesh/compiled-
                step-family rebuild at the reduced shard count
    rescale_restore  elastic re-plan only: the full rescaled restore
                (re-bucketing the cut over the re-sliced ranges)
    first_fire  recovery entry -> first post-restore window emission,
                the end-to-end MTTR number

Rows ride ``/jobs/<jid>/recovery`` and the ``recovery_*`` gauges on the
job's metric group (Prometheus exposition included); phases also land in
the PR 2 span tracer as ``recovery_<phase>`` spans so a slow recovery is
diagnosable in the same Perfetto timeline as the steady-state loop.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, List, Optional


class RecoveryTracker:
    """One per windowed job. Every phase of a recovery runs on the
    step-loop thread (the fires that complete an attempt too), but
    ``report`` is served from the web thread mid-recovery, so row
    mutations and the report snapshot synchronize on a small lock (held
    only around dict updates, never around a timed phase body)."""

    def __init__(self, group=None, tracer=None):
        self.tracer = tracer
        self._lock = threading.Lock()
        self.attempts: List[dict] = []     # bounded history (newest 50)
        # monotonic totals, independent of the bounded history ring — a
        # crash-looping job's 51st restart must still move the gauges
        self.total_attempts = 0
        self.total_warm = 0
        self.total_full = 0
        # elastic re-plans (runtime/elastic.py): completed rescales —
        # degrade AND scale-back — plus the live degraded-shard count
        # (full capacity minus current parallelism; 0 = not degraded)
        self.total_rescales = 0
        self.degraded_shards = 0
        self.local_cache: Any = None    # LocalSnapshotCache, set by owner
        self._open: Optional[dict] = None
        self._t0: float = 0.0
        self._compile_mark = None
        self._g = {}
        if group is not None:
            for name in ("recovery_attempts", "recovery_warm_restarts",
                         "recovery_full_restores", "recovery_rescales",
                         "degraded_shards"):
                self._g[name] = group.settable_gauge(name, 0)
            for name in ("recovery_last_total_ms",
                         "recovery_last_first_fire_ms"):
                self._g[name] = group.settable_gauge(name, 0.0)
            group.gauge(
                "recovery_local_hits",
                lambda: self.local_cache.stats["hits"]
                if self.local_cache is not None else 0,
            )
            group.gauge(
                "recovery_local_misses",
                lambda: self.local_cache.stats["misses"]
                if self.local_cache is not None else 0,
            )

    def _set(self, name, v):
        g = self._g.get(name)
        if g is not None:
            g.set(v)

    # -- attempt lifecycle ----------------------------------------------
    def begin(self, cause: str, classification: str,
              detect_s: float = 0.0) -> dict:
        """Open a recovery attempt. ``detect_s``: failure raise ->
        recovery entry (the watchdog's deadline wait is already inside
        the raise for hang failures)."""
        from flink_tpu.metrics.tracing import CompileEvents

        self._t0 = time.perf_counter()
        self._compile_mark = CompileEvents.mark()
        self._open = {
            "attempt": self.total_attempts + 1,
            "cause": cause[:300],
            "classification": classification,
            "mode": None,            # warm-splice | warm-full | full
            "restored_cid": None,
            "phases_ms": {"detect": round(detect_s * 1e3, 2)},
            "total_ms": None,
            "first_fire_ms": None,
            "ok": False,
        }
        with self._lock:
            self.attempts.append(self._open)
            del self.attempts[:-50]
            self.total_attempts += 1
        self._set("recovery_attempts", self.total_attempts)
        return self._open

    @contextlib.contextmanager
    def phase(self, name: str):
        """Time one recovery phase (accumulates: a retried restore adds
        to the same attempt's row)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if self._open is not None:
                ms = (time.perf_counter() - t0) * 1e3
                with self._lock:
                    ph = self._open["phases_ms"]
                    ph[name] = round(ph.get(name, 0.0) + ms, 2)
                if self.tracer is not None and self.tracer.active:
                    self.tracer.rec(f"recovery_{name}", t0)

    def mark_phase(self, name: str, t0: float, t1: float = None):
        """Record one phase from explicit perf_counter marks (for call
        sites where a with-block would contort the control flow)."""
        if self._open is None:
            return
        t1 = time.perf_counter() if t1 is None else t1
        with self._lock:
            ph = self._open["phases_ms"]
            ph[name] = round(ph.get(name, 0.0) + (t1 - t0) * 1e3, 2)
        if self.tracer is not None and self.tracer.active:
            self.tracer.rec(f"recovery_{name}", t0, t1)

    def set_mode(self, mode: str, restored_cid=None):
        if self._open is not None:
            with self._lock:
                self._open["mode"] = mode
                if restored_cid is not None:
                    self._open["restored_cid"] = int(restored_cid)

    def end(self):
        """Restore complete; the attempt closes fully at the first
        post-restore fire (note_fire)."""
        if self._open is None:
            return
        with self._lock:
            self._open["ok"] = True
            self._open["total_ms"] = round(
                (time.perf_counter() - self._t0) * 1e3, 2
            )
            if (self._open["mode"] or "").startswith("warm"):
                self.total_warm += 1
            elif self._open["mode"] == "full":
                self.total_full += 1
        self._set("recovery_last_total_ms", self._open["total_ms"])
        self._set("recovery_warm_restarts", self.total_warm)
        self._set("recovery_full_restores", self.total_full)

    def note_rescale(self, from_shards: int, to_shards: int,
                     degraded_shards: int):
        """One completed elastic re-plan (degrade or scale-back): bump
        the rescale total, publish the live degraded-shard count, and
        stamp the transition onto the open attempt's row (a scale-back
        runs outside any attempt — gauges still move)."""
        with self._lock:
            self.total_rescales += 1
            self.degraded_shards = max(0, int(degraded_shards))
            if self._open is not None:
                self._open["rescale"] = {
                    "from_shards": int(from_shards),
                    "to_shards": int(to_shards),
                }
        self._set("recovery_rescales", self.total_rescales)
        self._set("degraded_shards", self.degraded_shards)

    def note_fire(self):
        """Called by the fire drain on every emission: the FIRST one
        after a restore stamps detect-to-first-fire and the compile
        wall-time the recovery paid."""
        a = self._open
        if a is None or not a["ok"] or a["first_fire_ms"] is not None:
            return
        from flink_tpu.metrics.tracing import CompileEvents

        n, secs = CompileEvents.since(self._compile_mark)
        with self._lock:
            a["first_fire_ms"] = round(
                (time.perf_counter() - self._t0) * 1e3, 2
            )
            a["phases_ms"]["compile"] = round(secs * 1e3, 2)
            a["compiles"] = int(n)
            a["phases_ms"]["replay"] = round(
                max(0.0, a["first_fire_ms"] - a["total_ms"]), 2
            )
        self._set("recovery_last_first_fire_ms", a["first_fire_ms"])
        self._open = None

    # -- observability --------------------------------------------------
    def report(self) -> dict:
        """JSON-able snapshot for /jobs/<jid>/recovery. Deep-copies the
        rows under the lock: the web thread serializes this while the
        step-loop thread is still stamping phases into the open row."""
        with self._lock:
            attempts = [
                {**a, "phases_ms": dict(a["phases_ms"])}
                for a in self.attempts
            ]
        return {
            "attempts": attempts,
            "counts": {
                "total": self.total_attempts,
                "warm": self.total_warm,
                "full": self.total_full,
                "rescales": self.total_rescales,
                "degraded_shards": self.degraded_shards,
            },
            "local-cache": (
                self.local_cache.state()
                if self.local_cache is not None else None
            ),
        }
