"""Metrics (ref flink-metrics-core + runtime metric groups, SURVEY §5)."""

from flink_tpu.metrics.core import (
    Counter,
    Gauge,
    Histogram,
    JsonFileReporter,
    LoggingReporter,
    Meter,
    MetricGroup,
    MetricRegistry,
    Reporter,
    ScheduledReporter,
)
from flink_tpu.metrics.drain_stats import DRAIN_STAT_FIELDS, DrainTelemetry
from flink_tpu.metrics.tracing import CompileEvents, SpanTracer

__all__ = [
    "Counter", "Gauge", "Histogram", "Meter", "MetricGroup",
    "MetricRegistry", "Reporter", "JsonFileReporter", "LoggingReporter",
    "ScheduledReporter", "SpanTracer", "CompileEvents",
    "DrainTelemetry", "DRAIN_STAT_FIELDS",
]
