"""Wire-protocol metric reporters — the flink-metrics-* module analogs.

The reference ships reporter jars per system (flink-metrics-statsd
StatsDReporter.java, flink-metrics-graphite wrapping dropwizard's
GraphiteReporter, flink-metrics-jmx). Here each is a small class on the
same Reporter SPI (metrics/core.py), plus `configure_reporters` which
reads the reference's configuration shape:

    metrics.reporters: "stsd,graph"
    metrics.reporter.stsd.class: statsd
    metrics.reporter.stsd.host: 127.0.0.1
    metrics.reporter.stsd.port: 8125
    metrics.reporter.stsd.interval: 10       # seconds
    metrics.reporter.graph.class: graphite
    ...

(ref MetricRegistryConfiguration.fromConfiguration /
metrics.reporter.<name>.<option> keys). JMX has no analog outside a JVM;
the JSON-file and logging reporters (metrics/core.py) cover the
file/console roles.
"""

from __future__ import annotations

import socket
import time
from typing import Dict, List

from flink_tpu.metrics.core import (
    JsonFileReporter,
    LoggingReporter,
    MetricRegistry,
    Reporter,
    ScheduledReporter,
)


def _flatten(snapshot: Dict) -> Dict[str, float]:
    """Registry snapshot -> flat {path: numeric} (histograms expand to
    per-statistic paths, the dropwizard convention)."""
    out: Dict[str, float] = {}
    for k, v in snapshot.items():
        if isinstance(v, dict):
            for stat, sv in v.items():
                if isinstance(sv, (int, float)):
                    out[f"{k}.{stat}"] = sv
        elif isinstance(v, bool):
            out[k] = int(v)
        elif isinstance(v, (int, float)):
            out[k] = v
    return out


def _sanitize(path: str, sep: str = ".") -> str:
    out = []
    for ch in path:
        if ch.isalnum() or ch in ("-", "_", sep):
            out.append(ch)
        else:
            out.append("_")
    return "".join(out)


class StatsDReporter(Reporter):
    """StatsD line protocol over UDP (ref flink-metrics-statsd
    StatsDReporter.java:report): every numeric metric as a gauge
    `<path>:<value>|g`, one datagram per metric (the protocol's safe
    framing — servers may drop oversized batches silently)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125):
        self.addr = (host, int(port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def report(self):
        for path, v in _flatten(self.registry.snapshot()).items():
            line = f"{_sanitize(path)}:{v}|g"
            try:
                self._sock.sendto(line.encode(), self.addr)
            except OSError:
                pass      # UDP best-effort, like the reference

    def close(self):
        self._sock.close()


class GraphiteReporter(Reporter):
    """Graphite plaintext protocol over TCP (`<path> <value> <epoch>\\n`),
    reconnecting on failure (ref flink-metrics-graphite via dropwizard
    GraphiteReporter). One connection per report() batch."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2003,
                 prefix: str = "flink_tpu"):
        self.addr = (host, int(port))
        self.prefix = prefix

    def report(self):
        flat = _flatten(self.registry.snapshot())
        if not flat:
            return
        now = int(time.time())
        payload = "".join(
            f"{self.prefix}.{_sanitize(p)} {v} {now}\n"
            for p, v in flat.items()
        ).encode()
        try:
            with socket.create_connection(self.addr, timeout=5) as s:
                s.sendall(payload)
        except OSError:
            pass          # transient carbon outage: next interval retries

    def close(self):
        pass


_KINDS = {
    "statsd": StatsDReporter,
    "graphite": GraphiteReporter,
    "jsonfile": JsonFileReporter,
    "logging": LoggingReporter,
}


def stop_reporters(threads: List[ScheduledReporter],
                   registry: MetricRegistry):
    """Teardown half of configure_reporters: stop the scheduler threads
    and close every reporter's socket/file handle. Safe to call more
    than once; used as the environment's GC finalizer."""
    for t in threads:
        t.stop()
    try:
        registry.close()
    except Exception:
        pass


def configure_reporters(registry: MetricRegistry, config
                        ) -> List[ScheduledReporter]:
    """Instantiate + schedule the reporters named in `metrics.reporters`
    (ref MetricRegistryConfiguration). Returns the started scheduler
    threads (daemons; stop() them on env teardown, or let them die with
    the process like the reference's reporter executor)."""
    names = [
        n.strip()
        for n in config.get_str("metrics.reporters", "").split(",")
        if n.strip()
    ]
    # validate EVERY declared reporter before starting ANY thread: a
    # later typo'd class must not leak already-started threads/sockets
    # with no handle to stop them
    for name in names:
        kind = config.get_str(f"metrics.reporter.{name}.class", "")
        if kind not in _KINDS:
            raise ValueError(
                f"metrics.reporter.{name}.class must be one of "
                f"{sorted(_KINDS)}, got {kind!r}"
            )
    started: List[ScheduledReporter] = []
    for name in names:
        pre = f"metrics.reporter.{name}."
        cls = _KINDS[config.get_str(pre + "class", "")]
        if cls is StatsDReporter:
            rep = StatsDReporter(config.get_str(pre + "host", "127.0.0.1"),
                                 config.get_int(pre + "port", 8125))
        elif cls is GraphiteReporter:
            rep = GraphiteReporter(
                config.get_str(pre + "host", "127.0.0.1"),
                config.get_int(pre + "port", 2003),
                config.get_str(pre + "prefix", "flink_tpu"),
            )
        elif cls is JsonFileReporter:
            rep = JsonFileReporter(config.get_str(pre + "path",
                                                  "/tmp/flink_tpu_metrics.json"))
        else:
            rep = LoggingReporter()
        registry.add_reporter(rep)
        sched = ScheduledReporter(
            rep, config.get_float(pre + "interval", 10.0)
        )
        sched.start()
        started.append(sched)
    return started
