"""Wire-protocol metric reporters — the flink-metrics-* module analogs.

The reference ships reporter jars per system (flink-metrics-statsd
StatsDReporter.java, flink-metrics-graphite wrapping dropwizard's
GraphiteReporter, flink-metrics-jmx). Here each is a small class on the
same Reporter SPI (metrics/core.py), plus `configure_reporters` which
reads the reference's configuration shape:

    metrics.reporters: "stsd,graph"
    metrics.reporter.stsd.class: statsd
    metrics.reporter.stsd.host: 127.0.0.1
    metrics.reporter.stsd.port: 8125
    metrics.reporter.stsd.interval: 10       # seconds
    metrics.reporter.graph.class: graphite
    ...

(ref MetricRegistryConfiguration.fromConfiguration /
metrics.reporter.<name>.<option> keys). JMX has no analog outside a JVM;
the JSON-file and logging reporters (metrics/core.py) cover the
file/console roles.
"""

from __future__ import annotations

import socket
import time
from typing import Dict, List

from flink_tpu.metrics.core import (
    Counter,
    Gauge,
    Histogram,
    JsonFileReporter,
    LoggingReporter,
    Meter,
    MetricRegistry,
    Reporter,
    ScheduledReporter,
)


def _flatten(snapshot: Dict) -> Dict[str, float]:
    """Registry snapshot -> flat {path: numeric} (histograms expand to
    per-statistic paths, the dropwizard convention)."""
    out: Dict[str, float] = {}
    for k, v in snapshot.items():
        if isinstance(v, dict):
            for stat, sv in v.items():
                if isinstance(sv, (int, float)):
                    out[f"{k}.{stat}"] = sv
        elif isinstance(v, bool):
            out[k] = int(v)
        elif isinstance(v, (int, float)):
            out[k] = v
    return out


def _sanitize(path: str, sep: str = ".") -> str:
    out = []
    for ch in path:
        if ch.isalnum() or ch in ("-", "_", sep):
            out.append(ch)
        else:
            out.append("_")
    return "".join(out)


class StatsDReporter(Reporter):
    """StatsD line protocol over UDP (ref flink-metrics-statsd
    StatsDReporter.java:report): every numeric metric as a gauge
    `<path>:<value>|g`, one datagram per metric (the protocol's safe
    framing — servers may drop oversized batches silently)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125):
        self.addr = (host, int(port))
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def report(self):
        for path, v in _flatten(self.registry.snapshot()).items():
            line = f"{_sanitize(path)}:{v}|g"
            try:
                self._sock.sendto(line.encode(), self.addr)
            except OSError:
                pass      # UDP best-effort, like the reference

    def close(self):
        self._sock.close()


class GraphiteReporter(Reporter):
    """Graphite plaintext protocol over TCP (`<path> <value> <epoch>\\n`),
    reconnecting on failure (ref flink-metrics-graphite via dropwizard
    GraphiteReporter). One connection per report() batch."""

    def __init__(self, host: str = "127.0.0.1", port: int = 2003,
                 prefix: str = "flink_tpu"):
        self.addr = (host, int(port))
        self.prefix = prefix

    def report(self):
        flat = _flatten(self.registry.snapshot())
        if not flat:
            return
        now = int(time.time())
        payload = "".join(
            f"{self.prefix}.{_sanitize(p)} {v} {now}\n"
            for p, v in flat.items()
        ).encode()
        try:
            with socket.create_connection(self.addr, timeout=5) as s:
                s.sendall(payload)
        except OSError:
            pass          # transient carbon outage: next interval retries

    def close(self):
        pass


class GangliaReporter(Reporter):
    """Ganglia gmond protocol v3.1 over UDP (ref flink-metrics-ganglia,
    which wraps gmetric4j's GMetric). XDR-encoded per the public
    gm_protocol.x spec: a METADATA message (id 128: hostname, metric
    name, spoof flag, then type/name/units/slope/tmax/dmax + extras)
    followed by a DOUBLE VALUE message (id 135: hostname, name, spoof,
    printf format, IEEE-754 big-endian double). XDR strings are
    length-prefixed and zero-padded to 4-byte boundaries; all ints are
    4-byte big-endian. Metadata rides every report (dmax=0 servers
    drop metrics whose metadata aged out; resending is gmetric4j's
    periodic-announce behavior collapsed to the report interval)."""

    GMETADATA_FULL = 128
    GMETRIC_DOUBLE = 135
    SLOPE_BOTH = 3

    def __init__(self, host: str = "127.0.0.1", port: int = 8649,
                 tmax: int = 60, dmax: int = 0,
                 hostname: str = ""):
        self.addr = (host, int(port))
        self.tmax = tmax
        self.dmax = dmax
        self.hostname = hostname or socket.gethostname()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    @staticmethod
    def _xdr_int(v: int) -> bytes:
        return int(v).to_bytes(4, "big", signed=False)

    @staticmethod
    def _xdr_string(s: str) -> bytes:
        b = s.encode()
        pad = (4 - len(b) % 4) % 4
        return len(b).to_bytes(4, "big") + b + b"\x00" * pad

    def _metadata(self, name: str) -> bytes:
        x = (self._xdr_int(self.GMETADATA_FULL)
             + self._xdr_string(self.hostname)
             + self._xdr_string(name)
             + self._xdr_int(0)                 # spoof
             + self._xdr_string("double")       # type
             + self._xdr_string(name)
             + self._xdr_string("")             # units
             + self._xdr_int(self.SLOPE_BOTH)
             + self._xdr_int(self.tmax)
             + self._xdr_int(self.dmax)
             + self._xdr_int(0))                # no extra elements
        return x

    def _value(self, name: str, v: float) -> bytes:
        import struct as _struct

        return (self._xdr_int(self.GMETRIC_DOUBLE)
                + self._xdr_string(self.hostname)
                + self._xdr_string(name)
                + self._xdr_int(0)              # spoof
                + self._xdr_string("%f")
                + _struct.pack(">d", float(v)))

    def report(self):
        for path, v in _flatten(self.registry.snapshot()).items():
            name = _sanitize(path)
            try:
                self._sock.sendto(self._metadata(name), self.addr)
                self._sock.sendto(self._value(name, v), self.addr)
            except OSError:
                pass      # UDP best-effort, like the reference

    def close(self):
        self._sock.close()


# ------------------------------------------------------------- prometheus

def _prom_name(path: str) -> str:
    """Metric-name charset [a-zA-Z0-9_:]; everything else collapses."""
    out = []
    for ch in path:
        out.append(ch if (ch.isalnum() and ch.isascii()) or ch in "_:"
                   else "_")
    name = "".join(out)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def _prom_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _prom_split(scope: str):
    """`jobs.<job>.<metric>` -> (metric, {"job": <job>}); other scopes
    keep the full dotted path as the name with no labels. Job names ride
    as a LABEL (the Prometheus idiom: one series family per metric, jobs
    distinguished by label) instead of exploding the name space."""
    parts = scope.split(".")
    if len(parts) >= 3 and parts[0] == "jobs":
        return ".".join(parts[2:]), {"job": parts[1]}
    return scope, {}


def _prom_line(name: str, labels: dict, value) -> str:
    if labels:
        lbl = ",".join(
            f'{k}="{_prom_label(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{lbl}}} {value}"
    return f"{name} {value}"


def prometheus_text(registry: MetricRegistry, namespace: str = "flink_tpu",
                    prefix: str = "") -> str:
    """Render a registry in the Prometheus text exposition format
    (version 0.0.4 — the /metrics scrape payload).

    Counters -> `counter`; Gauges -> `gauge` (non-numeric values are
    skipped: exposition carries only numbers); Histograms -> `summary`
    (quantile series + _count + _sum, sum reconstructed as mean*count);
    Meters -> a `_total` counter plus a `_rate` gauge.
    """
    return prometheus_text_from_items(registry.items(prefix), namespace)


def prometheus_text_from_items(items, namespace: str = "flink_tpu") -> str:
    """Exposition over a merged [(scope, metric)] list — one TYPE header
    per metric family even when the scopes come from several registries
    (the web monitor scrapes every job's registry into ONE payload; a
    family may legally appear only once)."""
    families = {}     # name -> (type, [lines])

    def add(name, typ, labels, value):
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            if isinstance(value, bool):
                value = int(value)
            else:
                return
        fam = families.setdefault(name, (typ, []))
        fam[1].append(_prom_line(name, labels, value))

    for scope, metric in items:
        raw, labels = _prom_split(scope)
        name = _prom_name(f"{namespace}_{raw}" if namespace else raw)
        if isinstance(metric, Counter):
            add(name, "counter", labels, metric.get_count())
        elif isinstance(metric, Gauge):
            try:
                add(name, "gauge", labels, metric.get_value())
            except Exception:
                pass            # a broken gauge must not kill the scrape
        elif isinstance(metric, Histogram):
            snap = metric.snapshot()
            n = snap.get("count", 0)
            for q in ("p50", "p95", "p99"):
                if q in snap:
                    add(name, "summary",
                        {**labels, "quantile": f"0.{q[1:]}"}, snap[q])
            add(f"{name}_count", "summary", labels, n)
            if n:
                add(f"{name}_sum", "summary", labels,
                    snap["mean"] * n)
        elif isinstance(metric, Meter):
            add(f"{name}_total", "counter", labels, metric.get_count())
            add(f"{name}_rate", "gauge", labels, metric.get_rate())
    lines = []
    for name in sorted(families):
        typ, rows = families[name]
        # _count/_sum ride their parent summary family without their own
        # TYPE header (the exposition format treats them as one family)
        if not (name.endswith("_count") or name.endswith("_sum")) or \
                name[: name.rfind("_")] not in families:
            lines.append(f"# TYPE {name} {typ}")
        lines.extend(rows)
    return "\n".join(lines) + ("\n" if lines else "")


class PrometheusReporter(Reporter):
    """Pull-based Prometheus exposition (ref flink-metrics-prometheus
    PrometheusReporter.java — there an embedded HTTP server; here the
    existing web monitor serves /metrics on ITS port, no new listener).
    `scrape()` renders the current exposition text; `report()` is a no-op
    by design (Prometheus pulls), unless constructed with a `path` to
    also drop the exposition to a file each interval (the node-exporter
    textfile-collector pattern, for jobs with no web monitor)."""

    def __init__(self, namespace: str = "flink_tpu", path: str = ""):
        self.namespace = namespace
        self.path = path

    def scrape(self) -> str:
        return prometheus_text(self.registry, self.namespace)

    def report(self):
        if self.path:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                f.write(self.scrape())
            import os
            os.replace(tmp, self.path)   # atomic: scrapers never see half


_KINDS = {
    "statsd": StatsDReporter,
    "graphite": GraphiteReporter,
    "ganglia": GangliaReporter,
    "jsonfile": JsonFileReporter,
    "logging": LoggingReporter,
    "prometheus": PrometheusReporter,
}


def stop_reporters(threads: List[ScheduledReporter],
                   registry: MetricRegistry):
    """Teardown half of configure_reporters: stop the scheduler threads
    and close every reporter's socket/file handle. Safe to call more
    than once; used as the environment's GC finalizer."""
    for t in threads:
        t.stop()
    try:
        registry.close()
    except Exception:
        pass


def configure_reporters(registry: MetricRegistry, config
                        ) -> List[ScheduledReporter]:
    """Instantiate + schedule the reporters named in `metrics.reporters`
    (ref MetricRegistryConfiguration). Returns the started scheduler
    threads (daemons; stop() them on env teardown, or let them die with
    the process like the reference's reporter executor)."""
    names = [
        n.strip()
        for n in config.get_str("metrics.reporters", "").split(",")
        if n.strip()
    ]
    # validate EVERY declared reporter before starting ANY thread: a
    # later typo'd class must not leak already-started threads/sockets
    # with no handle to stop them
    for name in names:
        kind = config.get_str(f"metrics.reporter.{name}.class", "")
        if kind not in _KINDS:
            raise ValueError(
                f"metrics.reporter.{name}.class must be one of "
                f"{sorted(_KINDS)}, got {kind!r}"
            )
    started: List[ScheduledReporter] = []
    for name in names:
        pre = f"metrics.reporter.{name}."
        cls = _KINDS[config.get_str(pre + "class", "")]
        if cls is StatsDReporter:
            rep = StatsDReporter(config.get_str(pre + "host", "127.0.0.1"),
                                 config.get_int(pre + "port", 8125))
        elif cls is GraphiteReporter:
            rep = GraphiteReporter(
                config.get_str(pre + "host", "127.0.0.1"),
                config.get_int(pre + "port", 2003),
                config.get_str(pre + "prefix", "flink_tpu"),
            )
        elif cls is GangliaReporter:
            rep = GangliaReporter(
                config.get_str(pre + "host", "127.0.0.1"),
                config.get_int(pre + "port", 8649),
                config.get_int(pre + "tmax", 60),
                config.get_int(pre + "dmax", 0),
                config.get_str(pre + "hostname", ""),
            )
        elif cls is JsonFileReporter:
            rep = JsonFileReporter(config.get_str(pre + "path",
                                                  "/tmp/flink_tpu_metrics.json"))
        elif cls is PrometheusReporter:
            rep = PrometheusReporter(
                config.get_str(pre + "namespace", "flink_tpu"),
                config.get_str(pre + "path", ""),
            )
        else:
            rep = LoggingReporter()
        registry.add_reporter(rep)
        sched = ScheduledReporter(
            rep, config.get_float(pre + "interval", 10.0)
        )
        sched.start()
        started.append(sched)
    return started
