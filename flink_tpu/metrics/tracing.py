"""Step-loop span tracing + XLA compile visibility.

The reference samples per-record visibility out of a running job
(LatencyMarker sentinels, BackPressureStatsTracker stack sampling). The
micro-batch design makes that structurally impossible — and unnecessary:
every cycle of the executor's step loop decomposes EXACTLY into named
phases (source drain, key routing, device step dispatch, barrier/scalar
fetch, fire extraction, emit, checkpoint sync). The tracer records those
phases as spans into a bounded ring buffer and exports them as
Chrome-trace JSON (chrome://tracing / Perfetto `traceEvents` array), so a
tail-latency stall is attributable to a phase instead of a mystery
(Hazelcast Jet's 99.99%-ile work, PAPERS.md: tails come from rare
coordination stalls — here barrier fetches, transfers, recompiles).

Design constraints:
  * OFF by default. When off, the executor holds no tracer and the hot
    path pays nothing. When on, the per-span cost is two perf_counter()
    reads (usually reusing timestamps the cycle attribution already
    takes) and one deque.append of a tuple.
  * SAMPLED. `observability.trace-sample-every: N` records every N-th
    cycle only; the skipped cycles pay one integer compare.
  * BOUNDED. The ring holds `observability.trace-buffer-spans` records;
    old spans fall off — a perpetual job cannot grow host memory.

Compile visibility (`CompileEvents`): jax.monitoring emits an event per
XLA backend compile (`/jax/core/compile/backend_compile_duration`). One
process-wide listener counts them and records wall time, attributed to
the stage label the executor sets around its step builds/warmups — a
recompile storm shows up as a named counter moving, not a mystery stall.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

# span record layout: (name, stage, t_start_s, dur_s, attrs_or_None)
_Span = Tuple[str, str, float, float, Optional[dict]]

# the step-loop phases the executor instruments; exported for tests and
# the docs so the catalog cannot silently drift from the wiring
STEP_PHASES = (
    "source",           # source poll / prefetch wait + host chain/encode
    "route",            # per-batch exchange-route feasibility (key routing;
                        #   recorded from the ingest thread when planned
                        #   at prep time, runtime/ingest.py)
    "stage",            # ingest-thread pad into the staging ring
    "transfer",         # ingest-thread H2D device_put + completion wait
    "dispatch",         # device step dispatch (+ inflight-depth wait)
    "drain",            # resident ring-drain dispatch (pipeline.
                        #   resident-loop); attrs carry the slot count
    "fire",             # fire-step dispatch at a pane boundary
    "barrier_fetch",    # step-boundary scalar/lane fetch (the d2h barrier)
    "emit",             # fire extraction + sink invocation
    "checkpoint_sync",  # checkpoint sync phase (the only ckpt loop stall)
)


class SpanTracer:
    """Bounded ring buffer of step-loop phase spans.

    One tracer per job run, owned by the executor thread; `snapshot()`
    and the exporters may be called from web/reporter threads (the deque
    append/iterate pair is guarded by a lock — spans are tiny, the
    critical sections are nanoseconds).
    """

    def __init__(self, stage: str = "job", sample_every: int = 1,
                 max_spans: int = 65536):
        self.stage = stage
        self.sample_every = max(1, int(sample_every))
        self._spans: deque = deque(maxlen=max(16, int(max_spans)))
        # counter-track samples ride their own ring so a chatty counter
        # cannot evict spans: (track, t_sample_s, {series: value})
        self._counters: deque = deque(maxlen=max(16, int(max_spans)))
        self._lock = threading.Lock()
        # perf_counter origin for relative span timestamps + the wall
        # clock at that origin so exported ts can be absolute-ish
        self.t0 = time.perf_counter()
        self.epoch_ms = time.time() * 1000.0
        self._cycle = -1
        self.active = False       # does the CURRENT cycle record spans?
        self.dropped = 0          # spans recorded while ring was full

    # -- recording (executor thread) ------------------------------------
    def begin_cycle(self) -> bool:
        """Advance the cycle counter; returns whether this cycle records."""
        self._cycle += 1
        self.active = (self._cycle % self.sample_every) == 0
        return self.active

    def rec(self, name: str, t_start: float, t_end: Optional[float] = None,
            stage: Optional[str] = None, **attrs):
        """Record one span from perf_counter() timestamps. Callers guard
        with `if tr is not None and tr.active:` so the off path costs one
        attribute read."""
        if t_end is None:
            t_end = time.perf_counter()
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append((
                name, stage or self.stage, t_start, t_end - t_start,
                attrs or None,
            ))

    def rec_counter(self, track: str, t_sample: Optional[float] = None,
                    **values):
        """Record one sample on a Perfetto counter track ("ph": "C"):
        the drain flight recorder emits ring fill / duty cycle / events
        retired this way so they render as stacked counter lanes above
        the phase spans. Same guard discipline as `rec`."""
        if not values:
            return
        if t_sample is None:
            t_sample = time.perf_counter()
        with self._lock:
            self._counters.append((
                track, t_sample,
                {k: float(v) for k, v in values.items()},
            ))

    def span(self, name: str, **attrs):
        """Context-manager form for code paths without an existing
        timestamp pair (the executor's occupancy refresh uses it). The
        sampling decision is captured at ENTRY so a cycle boundary
        inside the block cannot split the decision."""
        return _SpanCtx(self, name, attrs)

    # -- export (any thread) --------------------------------------------
    def snapshot(self) -> List[_Span]:
        with self._lock:
            return list(self._spans)

    def counter_snapshot(self) -> List[Tuple[str, float, Dict[str, float]]]:
        with self._lock:
            return list(self._counters)

    def __len__(self):
        with self._lock:
            return len(self._spans)

    def to_chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace / Perfetto JSON object: complete ("ph": "X")
        events with microsecond timestamps relative to the tracer origin.
        Loadable directly in chrome://tracing and ui.perfetto.dev."""
        events = []
        for name, stage, t_start, dur, attrs in self.snapshot():
            ev = {
                "name": name,
                "cat": stage,
                "ph": "X",
                "ts": round((t_start - self.t0) * 1e6, 3),
                "dur": round(dur * 1e6, 3),
                "pid": 1,
                "tid": 1,
            }
            if attrs:
                ev["args"] = attrs
            events.append(ev)
        for track, t_sample, values in self.counter_snapshot():
            # Perfetto draws one stacked counter lane per (pid, name)
            # with the series keys of "args" as the stack components
            events.append({
                "name": track,
                "cat": "counter",
                "ph": "C",
                "ts": round((t_sample - self.t0) * 1e6, 3),
                "pid": 1,
                "args": values,
            })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "stage": self.stage,
                "sample_every": self.sample_every,
                "origin_epoch_ms": round(self.epoch_ms, 1),
                "spans_dropped": self.dropped,
            },
        }

    def dump(self, path: str) -> str:
        """Write the Chrome-trace JSON to a file; returns the path."""
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


class _SpanCtx:
    __slots__ = ("tracer", "name", "attrs", "t0", "active")

    def __init__(self, tracer: SpanTracer, name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self.active = self.tracer.active
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self.active:
            self.tracer.rec(self.name, self.t0, **self.attrs)
        return False


def tracer_from_config(config, stage: str = "job") -> Optional[SpanTracer]:
    """Build a SpanTracer from the `observability.*` config keys, or None
    when tracing is off (the default — the hot path then carries no
    tracer reference at all)."""
    if config is None or not config.get_bool("observability.tracing", False):
        return None
    return SpanTracer(
        stage=stage,
        sample_every=config.get_int("observability.trace-sample-every", 1),
        max_spans=config.get_int("observability.trace-buffer-spans", 65536),
    )


# ---------------------------------------------------------------- compiles

class CompileEvents:
    """Process-wide XLA compile accounting via jax.monitoring.

    jax has exactly one global listener list, so this is a singleton:
    `install()` registers once and is idempotent. Each job snapshots the
    counters at start (`mark()`) and exposes deltas as gauges — per-job
    attribution over a process-global event stream, the same shape the
    reference uses for JVM-global GC counters on per-job dashboards.

    The executor labels compile bursts with `set_stage(...)` around its
    step builds/warmups; an event arriving outside any labelled section
    attributes to "steady". Small eager ops (device_put, tiny zeros)
    also compile once per shape and land there, so the recompile-storm
    alarm is a steady count that keeps GROWING while the job is in
    steady state — the loop dispatches only pre-compiled steps, so
    sustained growth means per-batch recompilation (a shape leak).
    """

    _lock = threading.Lock()
    _installed = False
    _stage = "steady"
    # stage -> {"count": int, "time_s": float}
    _by_stage: Dict[str, Dict[str, float]] = {}
    total_count = 0
    total_time_s = 0.0
    # per-event sinks (e.g. a job's compile-time histogram); jobs MUST
    # remove_sink on teardown or the process-global list leaks closures
    _sinks: List[Any] = []
    # trace-phase durations worth exporting alongside backend compiles
    _EVENT = "/jax/core/compile/backend_compile_duration"
    # with the persistent compilation cache on, a cache HIT skips
    # backend_compile entirely and emits this retrieval event instead —
    # count it as a compile (an executable still materialized for a new
    # signature; the jit cache absorbs true repeats, so storm semantics
    # are unchanged) or compile-count gauges would read 0 on cached runs
    _EVENT_CACHED = "/jax/compilation_cache/cache_retrieval_time_sec"

    @classmethod
    def install(cls):
        with cls._lock:
            if cls._installed:
                return
            try:
                from jax import monitoring
                monitoring.register_event_duration_secs_listener(
                    cls._on_duration
                )
            except Exception:
                # observability must never kill the job; without the
                # monitoring API the counters just stay at zero
                return
            cls._installed = True

    @classmethod
    def _on_duration(cls, event: str, duration_s: float, **kw):
        if event != cls._EVENT and event != cls._EVENT_CACHED:
            return
        with cls._lock:
            cls.total_count += 1
            cls.total_time_s += duration_s
            row = cls._by_stage.setdefault(
                cls._stage, {"count": 0, "time_s": 0.0}
            )
            row["count"] += 1
            row["time_s"] += duration_s
            sinks = list(cls._sinks)
        for s in sinks:      # outside the lock: sinks may take their own
            try:
                s(duration_s)
            except Exception:
                pass         # observability must never kill a compile

    @classmethod
    def add_sink(cls, fn):
        with cls._lock:
            cls._sinks.append(fn)
        return fn

    @classmethod
    def remove_sink(cls, fn):
        with cls._lock:
            if fn in cls._sinks:
                cls._sinks.remove(fn)

    @classmethod
    def set_stage(cls, stage: str):
        with cls._lock:
            cls._stage = stage

    @classmethod
    def stage(cls, name: str):
        """Context manager labelling compiles triggered inside the block."""
        return _StageCtx(cls, name)

    @classmethod
    def mark(cls) -> Tuple[int, float]:
        """(count, time_s) baseline for per-job delta gauges."""
        with cls._lock:
            return cls.total_count, cls.total_time_s

    @classmethod
    def since(cls, mark: Tuple[int, float]) -> Tuple[int, float]:
        with cls._lock:
            return (cls.total_count - mark[0],
                    cls.total_time_s - mark[1])

    @classmethod
    def report(cls) -> Dict[str, Any]:
        with cls._lock:
            return {
                "compiles": cls.total_count,
                "compile_time_ms": round(cls.total_time_s * 1e3, 2),
                "by_stage": {
                    k: {"count": v["count"],
                        "time_ms": round(v["time_s"] * 1e3, 2)}
                    for k, v in sorted(cls._by_stage.items())
                },
            }


class _StageCtx:
    __slots__ = ("cls", "name", "prev")

    def __init__(self, cls, name):
        self.cls = cls
        self.name = name

    def __enter__(self):
        with self.cls._lock:
            self.prev = self.cls._stage
            self.cls._stage = self.name
        return self

    def __exit__(self, *exc):
        with self.cls._lock:
            self.cls._stage = self.prev
        return False


def cost_analysis_of(jitted, *args) -> Optional[Dict[str, float]]:
    """FLOPs / bytes-accessed of one compiled step via the AOT
    `lower().compile().cost_analysis()` path, where the backend provides
    it (CPU and TPU do; some runtimes return None). This triggers a
    second trace+compile of the function, so callers gate it behind
    `observability.compile-cost` — it is a diagnosis tool, not an
    always-on probe."""
    try:
        ca = jitted.lower(*args).compile().cost_analysis()
    except Exception:
        return None
    if ca is None:
        return None
    # jax returns either a dict or a 1-element list of dicts by version
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    out = {}
    for k in ("flops", "bytes accessed"):
        v = ca.get(k)
        if isinstance(v, (int, float)):
            out[k.replace(" ", "_")] = float(v)
    return out or None
