"""Pipeline doctor: a ranked-findings diagnostics engine (ISSUE 17).

PR 14 gave the runtime eyes (drain duty-cycle, ring-starved EWMAs,
occupancy series, latency percentiles) and PR 17 extends them into
chained drains plus a key-group heat series — but an operator staring
at six telemetry planes still has to JOIN them by hand to answer "what
should I change". This module is that join: a pure host-side rule
engine over one consolidated snapshot dict, producing ranked findings
where every finding carries its evidence values AND a concrete config
remedy (the key to turn plus a suggestion), so the diagnosis is
actionable, never just descriptive.

The snapshot is plain JSON-shaped data the executor already serves:

  * ``pipeline``   — DrainTelemetry.report() (shards, stages, kg_heat)
  * ``metrics``    — JobMetrics counter fields (watchdog trips,
                     aborted/declined checkpoints, drops, restarts)
  * ``checkpoints``— the bounded checkpoint_stats history
  * ``compile``    — CompileEvents.report() (per-stage compile counts)
  * ``recovery``   — RecoveryTracker.report()
  * ``fire_latency_ms`` — JobMetrics fire-latency percentiles

Every rule degrades gracefully on a missing plane (no finding, never a
crash), so the doctor runs against partial snapshots — a job without
checkpointing simply cannot burn a checkpoint budget.

Served three ways (all the same engine): ``GET /jobs/<jid>/doctor``,
``python -m flink_tpu.doctor`` (exit codes 0 clean / 1 findings /
2 error, mirroring tools.lint), and in-process via
``env._doctor_report()``.

This module is on the hot-path-sync lint list (tools/lint/rules/
hot_path_sync.py): pure host arithmetic over already-fetched data —
no jax import, no device sync may creep in.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

DOCTOR_SCHEMA_VERSION = 1

# severity order for ranking (lower = more severe = first)
_SEVERITY_RANK = {"critical": 0, "warning": 1, "info": 2}

# tunable trigger levels; the executor overrides these from the
# observability.doctor.* config keys
DEFAULT_THRESHOLDS: Dict[str, float] = {
    # ring-starved EWMA fraction above which the publish side is the
    # bottleneck (the drain keeps finding an empty ring)
    "starved": 0.5,
    # duty-cycle EWMA above which every drain retires a full ring
    "saturated": 0.9,
    # peak edge demand / exchange-lanes budget ratio that warns BEFORE
    # the edge drops
    "edge_utilization": 0.8,
    # kg-heat max/mean ratio that marks a shard re-slice candidate
    "kg_skew": 4.0,
    # steady-bucket XLA compiles beyond which something recompiles
    # per batch (steady state dispatches pre-compiled steps only)
    "recompile": 8,
    # tier swaps (demotes + promotes) per drain above which residency
    # churns faster than the working set justifies
    "tier_churn": 0.5,
    # prefetch-miss fraction above which the prefetcher promotes
    # groups that never get touched before re-demotion
    "tier_miss": 0.5,
}


def _finding(rule: str, severity: str, score: float, summary: str,
             evidence: Dict[str, Any], remedy_key: str,
             remedy_suggestion: str,
             action: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    out = {
        "rule": rule,
        "severity": severity,
        "score": round(float(score), 4),
        "summary": summary,
        "evidence": evidence,
        "remedy": {"key": remedy_key, "suggestion": remedy_suggestion},
    }
    if action is not None:
        # machine-actionable arm of the remedy: names a registered
        # RuntimeController actuator (runtime/controller.py
        # ACTUATOR_NAMES — the contract lint in tests/test_doctor.py
        # pins the two lists together) so the self-tuning loop can
        # apply the same advice the human-facing remedy describes
        out["action"] = action
    return out


# ---------------------------------------------------------------- rules

def _rule_ring_starved(snap, th):
    pipe = snap.get("pipeline") or {}
    shards = pipe.get("shards") or []
    starved = [(s.get("shard", i), float(s.get("ring_starved", 0.0)))
               for i, s in enumerate(shards)]
    hot = [(s, v) for s, v in starved if v >= th["starved"]]
    if not hot:
        return None
    worst = max(v for _, v in hot)
    return _finding(
        "ring-starved", "warning", worst,
        f"{len(hot)}/{max(1, len(starved))} shard ring(s) are starved "
        f"(worst EWMA {worst:.2f} >= {th['starved']}): the drain keeps "
        f"finding an empty ring, so the device idles between "
        f"dispatches while ingest catches up",
        {
            "threshold": th["starved"],
            "shards": [
                {"shard": s, "ring_starved": round(v, 4)}
                for s, v in hot
            ],
        },
        "pipeline.prefetch-depth",
        "raise pipeline.prefetch-depth (and check the source poll "
        "rate) so the publish side keeps the ring fed between drains",
        action={"actuator": "ring-fill-target", "direction": "down"},
    )


def _rule_device_saturated(snap, th):
    pipe = snap.get("pipeline") or {}
    shards = pipe.get("shards") or []
    duties = [(s.get("shard", i), float(s.get("duty_cycle", 0.0)))
              for i, s in enumerate(shards)]
    hot = [(s, v) for s, v in duties if v >= th["saturated"]]
    if not hot:
        return None
    worst = max(v for _, v in hot)
    return _finding(
        "device-saturated", "warning", worst,
        f"{len(hot)}/{max(1, len(duties))} shard(s) run at full drain "
        f"duty (worst EWMA {worst:.2f} >= {th['saturated']}): every "
        f"drain retires a full ring, so the device is the bottleneck "
        f"and publishes queue behind it",
        {
            "threshold": th["saturated"],
            "shards": [
                {"shard": s, "duty_cycle": round(v, 4)} for s, v in hot
            ],
        },
        "pipeline.ring-depth",
        "raise pipeline.ring-depth (more slots retire per dispatch) "
        "and/or pipeline.steps-per-dispatch to amortize the fixed "
        "dispatch cost over more work",
        action={"actuator": "ring-fill-target", "direction": "up"},
    )


def _rule_edge_lane_overflow(snap, th):
    pipe = snap.get("pipeline") or {}
    stages = pipe.get("stages") or []
    worst = None
    for row in stages:
        util = row.get("edge_utilization")
        dropped = int((row.get("totals") or {}).get("dropped_capacity", 0))
        if dropped > 0:
            cand = ("critical", 1.0 + dropped, row, util, dropped)
        elif util is not None and float(util) >= th["edge_utilization"]:
            cand = ("warning", float(util), row, util, dropped)
        else:
            continue
        if worst is None or cand[1] > worst[1]:
            worst = cand
    if worst is None:
        return None
    severity, score, row, util, dropped = worst
    stage = row.get("stage")
    budget = row.get("edge_lane_budget")
    demand = row.get("edge_peak_demand")
    if dropped > 0:
        summary = (
            f"stage {stage}'s inter-stage edge OVERFLOWED: {dropped} "
            f"fire lane(s) dropped against the "
            f"{budget}-lane exchange budget (peak demand {demand})"
        )
    else:
        summary = (
            f"stage {stage}'s inter-stage edge is near overflow: peak "
            f"demand {demand} of {budget} lanes "
            f"({float(util):.0%} >= {th['edge_utilization']:.0%})"
        )
    return _finding(
        "edge-lane-overflow", severity, score, summary,
        {
            "threshold": th["edge_utilization"],
            "stage": stage,
            "edge_lane_budget": budget,
            "edge_peak_demand": demand,
            "edge_utilization": util,
            "dropped_capacity": dropped,
        },
        "pipeline.stages.exchange-lanes",
        "raise pipeline.stages.exchange-lanes above the peak per-drain "
        "fire demand (distinct keys x panes closing per drain)",
    )


def _rule_kg_heat_skew(snap, th):
    pipe = snap.get("pipeline") or {}
    kg = pipe.get("kg_heat") or {}
    if not kg.get("available"):
        return None
    skew = float(kg.get("skew_ratio") or 0.0)
    if skew < th["kg_skew"]:
        return None
    top = (kg.get("top") or [])[:3]
    cold = kg.get("cold_tail") or {}
    return _finding(
        "kg-heat-skew", "warning", skew,
        f"key-group heat is skewed {skew:.1f}x over the mean "
        f"(>= {th['kg_skew']}x): a few hot groups dominate one "
        f"shard's drain while the cold tail "
        f"({cold.get('fraction', 0):.0%} of groups) stays idle — a "
        f"shard re-slice candidate",
        {
            "threshold": th["kg_skew"],
            "skew_ratio": skew,
            "hot_groups": top,
            "cold_tail": cold,
        },
        "pipeline.data-parallel",
        "re-slice the shard key-group ranges around the hot groups "
        "(the savepoint-cut rescale path), or raise parallelism so "
        "the hot groups spread over more shards",
        action={"actuator": "rebalance-key-groups"},
    )


def _rule_recompile_storm(snap, th):
    comp = snap.get("compile") or {}
    steady = ((comp.get("by_stage") or {}).get("steady") or {})
    count = int(steady.get("count", 0))
    if count <= th["recompile"]:
        return None
    # a storm recompiles roughly once per dispatch; a fixed handful of
    # one-time shapes (end-of-stream flush, stragglers) does not scale
    # with volume, so when the metrics plane is present require the
    # steady count to track dispatches before crying wolf
    m = snap.get("metrics") or {}
    dispatches = (int(m.get("steps", 0))
                  + int(m.get("fused_dispatches", 0))
                  + int(m.get("resident_drains", 0)))
    if dispatches > 0 and count < 0.5 * dispatches:
        return None
    return _finding(
        "recompile-storm", "critical", float(count),
        f"{count} XLA compiles landed in the steady bucket "
        f"(> {int(th['recompile'])}): steady state should dispatch "
        f"only pre-compiled steps, so something recompiles per batch "
        f"(usually a shape leak)",
        {
            "threshold": int(th["recompile"]),
            "steady_compiles": count,
            "steady_compile_time_ms": steady.get("time_ms"),
            "total_compiles": comp.get("compiles"),
            "dispatches": dispatches,
        },
        "pipeline.steps-per-dispatch",
        "find the shape leak (env._compile_report() names the stages); "
        "pin batch shapes or lower pipeline.steps-per-dispatch so one "
        "signature serves every dispatch",
        action={"actuator": "dispatch-group", "direction": "down"},
    )


def _rule_checkpoint_budget_burn(snap, th):
    m = snap.get("metrics") or {}
    aborted = int(m.get("checkpoints_aborted", 0))
    declined = int(m.get("checkpoints_declined", 0))
    if aborted <= 0:
        return None
    rows = [r for r in (snap.get("checkpoints") or [])
            if r.get("status") == "aborted"]
    return _finding(
        "checkpoint-budget-burn", "warning", float(aborted),
        f"{aborted} checkpoint(s) aborted-and-counted against the "
        f"failure budget ({declined} trigger(s) declined): the budget "
        f"is burning down toward escalation",
        {
            "checkpoints_aborted": aborted,
            "checkpoints_declined": declined,
            "recent_aborts": [
                {"id": r.get("id"),
                 "failure_reason": r.get("failure_reason")}
                for r in rows[-3:]
            ],
        },
        "checkpoint.tolerable-failures",
        "fix the abort cause (recent_aborts names it) or raise "
        "checkpoint.tolerable-failures / the checkpoint interval so "
        "transient faults stop burning the budget",
    )


def _rule_ring_refusals(snap, th):
    pipe = snap.get("pipeline") or {}
    shards = pipe.get("shards") or []
    rows = [(s.get("shard", i), int(s.get("publish_refusals", 0)))
            for i, s in enumerate(shards)]
    hot = [(s, v) for s, v in rows if v > 0]
    if not hot:
        return None
    total = sum(v for _, v in hot)
    return _finding(
        "ring-refusals", "info", float(total),
        f"{total} staged batch(es) were refused by a full ring lane "
        f"across {len(hot)} shard(s) — publishes fell back to fresh "
        f"buffers, costing an extra H2D copy each",
        {
            "total_refusals": total,
            "shards": [
                {"shard": s, "publish_refusals": v} for s, v in hot
            ],
        },
        "pipeline.ring-depth",
        "raise pipeline.ring-depth so the ring absorbs the publish "
        "burst, or lower pipeline.prefetch-depth to slow the producer",
    )


def _rule_watchdog_trips(snap, th):
    m = snap.get("metrics") or {}
    trips = int(m.get("watchdog_trips", 0))
    if trips <= 0:
        return None
    return _finding(
        "watchdog-trips", "warning", float(trips),
        f"{trips} watchdog deadline trip(s): a step-loop phase "
        f"exceeded its deadline (the trip names the phase) — a hang "
        f"was converted into an attributed failure",
        {"watchdog_trips": trips,
         "restarts": int(m.get("restarts", 0))},
        "watchdog.drain-timeout",
        "if the tripped phase is legitimately slow (cold compile, "
        "giant restore), raise its watchdog.*-timeout; otherwise "
        "treat the trip as the failure it contained",
    )


def _rule_tier_thrash(snap, th):
    pipe = snap.get("pipeline") or {}
    tiers = pipe.get("tiers")
    if not tiers:
        return None
    swaps = int(tiers.get("demotes", 0)) + int(tiers.get("promotes", 0))
    hits = int(tiers.get("prefetch_hits", 0))
    misses = int(tiers.get("prefetch_misses", 0))
    m = snap.get("metrics") or {}
    drains = (int(m.get("resident_drains", 0))
              + int(m.get("steps", 0))
              + int(m.get("fused_dispatches", 0)))
    churn = swaps / drains if drains > 0 else 0.0
    miss_frac = misses / (hits + misses) if (hits + misses) > 0 else 0.0
    churny = drains > 0 and churn >= th["tier_churn"]
    missy = (hits + misses) >= 4 and miss_frac >= th["tier_miss"]
    if not (churny or missy):
        return None
    if churny:
        summary = (
            f"tiered state is thrashing: {swaps} residency swap(s) over "
            f"{drains} dispatch(es) ({churn:.2f}/dispatch >= "
            f"{th['tier_churn']}) — demote/promote splices burn host-"
            f"device copies faster than the working set justifies"
        )
        score = churn
        action = None
    else:
        summary = (
            f"tier prefetch is mispredicting: {misses}/{hits + misses} "
            f"promoted group(s) were never touched before re-demotion "
            f"({miss_frac:.0%} >= {th['tier_miss']:.0%})"
        )
        score = miss_frac
        # only the miss arm is machine-actionable: backing off the
        # prefetch horizon is safe; the churn arm's remedy (grow the
        # resident budget) changes memory shape, which stays a human
        # decision
        action = {"actuator": "tier-prefetch-ahead", "direction": "down"}
    return _finding(
        "tier-thrash", "warning", score, summary,
        {
            "churn_threshold": th["tier_churn"],
            "miss_threshold": th["tier_miss"],
            "demotes": int(tiers.get("demotes", 0)),
            "promotes": int(tiers.get("promotes", 0)),
            "dispatches": drains,
            "prefetch_hits": hits,
            "prefetch_misses": misses,
            "tier_faults": int(tiers.get("faults", 0)),
            "budget_per_shard": tiers.get("budget_per_shard"),
            "resident_groups": tiers.get("resident_groups"),
            "cold_groups_pending": tiers.get("cold_groups_pending"),
        },
        "state.tiers.resident-key-groups",
        "raise state.tiers.resident-key-groups so the hot set fits, or "
        "raise state.tiers.min-dwell-cycles to damp the churn; if the "
        "misses dominate, lower state.tiers.prefetch-ahead-panes so "
        "promotion waits for firmer watermark evidence",
        action=action,
    )


_RULES: List[Callable] = [
    _rule_ring_starved,
    _rule_device_saturated,
    _rule_edge_lane_overflow,
    _rule_kg_heat_skew,
    _rule_recompile_storm,
    _rule_checkpoint_budget_burn,
    _rule_ring_refusals,
    _rule_watchdog_trips,
    _rule_tier_thrash,
]

RULE_NAMES = tuple(
    r.__name__.replace("_rule_", "").replace("_", "-") for r in _RULES
)


def run_rules(snapshot: Dict[str, Any],
              thresholds: Optional[Dict[str, float]] = None
              ) -> List[Dict[str, Any]]:
    """Evaluate every rule over ``snapshot``; returns findings ranked
    most-severe first (severity class, then score descending)."""
    th = dict(DEFAULT_THRESHOLDS)
    if thresholds:
        th.update({k: v for k, v in thresholds.items() if v is not None})
    findings = []
    for rule in _RULES:
        f = rule(snapshot, th)
        if f is not None:
            findings.append(f)
    findings.sort(
        key=lambda f: (_SEVERITY_RANK.get(f["severity"], 9), -f["score"])
    )
    return findings


def diagnose(snapshot: Dict[str, Any],
             thresholds: Optional[Dict[str, float]] = None
             ) -> Dict[str, Any]:
    """The full doctor payload: the stable ``--json`` / web schema."""
    findings = run_rules(snapshot, thresholds)
    return {
        "available": True,
        "version": DOCTOR_SCHEMA_VERSION,
        "clean": not findings,
        "findings": findings,
        "rules": list(RULE_NAMES),
    }
