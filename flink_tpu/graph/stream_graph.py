"""Transformation DAG recorded by the DataStream API.

Role of the reference's StreamTransformation / StreamGraph /
StreamingJobGraphGenerator chain (SURVEY §2.5): API calls record immutable
nodes; at execute() the graph is translated into pipeline *stages*. Where the
reference fuses chainable operators into JobVertex chains
(StreamingJobGraphGenerator.createChain:172), we fuse every stateless host op
between two keyed boundaries into one chain list, and each keyed window
aggregation into one compiled SPMD stage — the TPU analog of operator
chaining (fusion happens again, at the XLA level, inside the stage).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

_ids = itertools.count()


@dataclass
class Transformation:
    name: str
    parent: Optional["Transformation"] = None
    id: int = field(default_factory=lambda: next(_ids))


@dataclass
class SourceTransformation(Transformation):
    source: Any = None  # runtime.sources.Source


@dataclass
class OneInputTransformation(Transformation):
    kind: str = "map"  # map | filter | flat_map | process
    fn: Callable = None


@dataclass
class TimestampsWatermarksTransformation(Transformation):
    timestamp_fn: Callable = None   # element -> epoch ms
    strategy: Any = None            # runtime.watermarks.WatermarkStrategy


@dataclass
class KeyByTransformation(Transformation):
    key_selector: Callable = None


@dataclass
class WindowAggTransformation(Transformation):
    assigner: Any = None            # window.assigners.WindowAssigner
    extractor: Callable = None      # element -> numeric value (host)
    reduce_spec_factory: Callable = None  # () -> ReduceSpec
    result_fn: Optional[Callable] = None  # acc -> output value (host, vectorized)
    value_prep: Optional[Callable] = None  # raw values array -> device values
    allowed_lateness_ms: int = 0
    # custom trigger/evictor/raw-elements function route the stage to the
    # generic host window operator instead of the device kernels
    trigger: Any = None             # window.triggers.Trigger
    evictor: Any = None             # window.evictors.Evictor
    window_fn: Optional[Callable] = None  # (key, window, elements) -> iter


@dataclass
class KeyedProcessTransformation(Transformation):
    """Keyed rolling aggregation (StreamGroupedReduce analog)."""

    reduce_spec_factory: Callable = None
    extractor: Callable = None
    result_fn: Optional[Callable] = None


@dataclass
class ProcessTransformation(Transformation):
    """Keyed ProcessFunction stage (host generality path: arbitrary user
    logic over heap keyed state + timers; ref StreamTimelyFlatMap)."""

    fn: Any = None  # datastream.functions.ProcessFunction


@dataclass
class SinkTransformation(Transformation):
    sink: Any = None  # runtime.sinks.Sink


def lineage(t: Transformation) -> List[Transformation]:
    """Walk parents to the source, returning [source, ..., t]."""
    chain = []
    cur = t
    while cur is not None:
        chain.append(cur)
        cur = cur.parent
    return list(reversed(chain))
