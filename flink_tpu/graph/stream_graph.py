"""Transformation DAG recorded by the DataStream API.

Role of the reference's StreamTransformation / StreamGraph /
StreamingJobGraphGenerator chain (SURVEY §2.5): API calls record immutable
nodes; at execute() the graph is translated into pipeline *stages*. Where the
reference fuses chainable operators into JobVertex chains
(StreamingJobGraphGenerator.createChain:172), we fuse every stateless host op
between two keyed boundaries into one chain list, and each keyed window
aggregation into one compiled SPMD stage — the TPU analog of operator
chaining (fusion happens again, at the XLA level, inside the stage).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

_ids = itertools.count()


@dataclass
class Transformation:
    name: str
    parent: Optional["Transformation"] = None
    id: int = field(default_factory=lambda: next(_ids))


@dataclass
class SourceTransformation(Transformation):
    source: Any = None  # runtime.sources.Source


@dataclass
class OneInputTransformation(Transformation):
    kind: str = "map"  # map | filter | flat_map | process
    fn: Callable = None


@dataclass
class TimestampsWatermarksTransformation(Transformation):
    timestamp_fn: Callable = None   # element -> epoch ms
    strategy: Any = None            # runtime.watermarks.WatermarkStrategy


@dataclass
class KeyByTransformation(Transformation):
    key_selector: Callable = None


@dataclass
class WindowAggTransformation(Transformation):
    assigner: Any = None            # window.assigners.WindowAssigner
    extractor: Callable = None      # element -> numeric value (host)
    reduce_spec_factory: Callable = None  # () -> ReduceSpec
    result_fn: Optional[Callable] = None  # acc -> output value (host, vectorized)
    value_prep: Optional[Callable] = None  # raw values array -> device values
    allowed_lateness_ms: int = 0
    # custom trigger/evictor/raw-elements function route the stage to the
    # generic host window operator instead of the device kernels
    trigger: Any = None             # window.triggers.Trigger
    evictor: Any = None             # window.evictors.Evictor
    window_fn: Optional[Callable] = None  # (key, window, elements) -> iter


@dataclass
class KeyedProcessTransformation(Transformation):
    """Keyed rolling aggregation (StreamGroupedReduce analog)."""

    reduce_spec_factory: Callable = None
    extractor: Callable = None
    result_fn: Optional[Callable] = None


@dataclass
class ProcessTransformation(Transformation):
    """Keyed ProcessFunction stage (host generality path: arbitrary user
    logic over heap keyed state + timers; ref StreamTimelyFlatMap)."""

    fn: Any = None  # datastream.functions.ProcessFunction


@dataclass
class SinkTransformation(Transformation):
    sink: Any = None  # runtime.sinks.Sink


@dataclass
class UnionTransformation(Transformation):
    """N-input merge (ref DataStream.union / the TaggedUnion lowering the
    reference uses for ConnectedStreams and CoGroupedStreams —
    CoGroupedStreams.java WithWindow.apply builds union + WindowOperator).

    `parent` stays None; the executor recursively translates each branch in
    `parents` into (source, chain, ts) and merges them with a MergedSource.
    When `tagged`, elements are wrapped as Tagged(tag, value) so downstream
    co-operators can dispatch per input.
    """

    parents: List[Transformation] = field(default_factory=list)
    tagged: bool = False


@dataclass
class IterateTransformation(Transformation):
    """Streaming iteration head (ref IterativeStream / StreamIterationHead +
    StreamIterationTail connected by BlockingQueueBroker, SURVEY §2.5).
    `queue` is the in-process feedback channel: close_with attaches a hidden
    QueueSink branch writing into it, and the head source drains it after
    the upstream is exhausted. Terminates when the feedback drains (the
    finite-source adaptation of the reference's iteration-wait timeout)."""

    queue: Any = None  # collections.deque shared with the feedback QueueSink
    max_wait_ms: int = 0  # accepted for API parity; drain-based termination


@dataclass
class PartitionTransformation(Transformation):
    """Explicit exchange annotation (ref Rebalance/Rescale/Shuffle/Broadcast/
    Global/ForwardPartitioner, SURVEY §2.5). On this architecture the
    keyed all_to_all inside the compiled SPMD step is the main physical
    exchange. Single-host, non-keyed repartitioning of the host
    micro-batch stream is a no-op (one host loop feeds the whole mesh)
    and the annotation is recorded for graph fidelity. On the MULTI-HOST
    path (dcn.coordinator configured), rebalance/shuffle/global are
    PHYSICAL at the ingestion edge: rebalance borrows ring-neighbor
    backlog into spare lanes, shuffle routes every record to a uniformly
    random host via the targeted ring, global routes everything to host
    0 (runtime/dcn.py _RebalanceRing/_TargetRing; executor._run_dcn
    reads the annotation). rescale stays host-local by definition."""

    mode: str = "rebalance"  # rebalance|rescale|shuffle|broadcast|global|forward


def lineage(t: Transformation) -> List[Transformation]:
    """Walk parents to the source (or union head), returning [head, ..., t]."""
    chain = []
    cur = t
    while cur is not None:
        chain.append(cur)
        cur = cur.parent
    return list(reversed(chain))


def walk_dag(sinks) -> List[Transformation]:
    """Every transformation reachable from `sinks`, topologically ordered
    (all inputs precede their node). The ONE reachability walk shared by
    the web plan handler and the ExecutionGraph builder, so the two
    views cannot disagree on the node set (ref StreamGraph traversal)."""
    order: List[Transformation] = []
    seen = set()

    def walk(t):
        if t is None or t.id in seen:
            return
        seen.add(t.id)
        for p in parents_of(t):
            walk(p)
        order.append(t)

    for s in sinks:
        walk(s)
    return order


def parents_of(t: Transformation) -> List[Transformation]:
    """All upstream transformations (single parent + union parents)."""
    out = [t.parent] if getattr(t, "parent", None) is not None else []
    out += list(getattr(t, "parents", []) or [])
    return out
