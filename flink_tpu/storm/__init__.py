"""Storm compatibility layer (ref flink-contrib/flink-storm)."""

from flink_tpu.storm.topology import (
    BasicBolt, BasicSpout, FlinkTopology, TopologyBuilder,
)

__all__ = ["TopologyBuilder", "FlinkTopology", "BasicSpout", "BasicBolt"]
