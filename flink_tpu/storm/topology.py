"""Storm topology compatibility — the flink-storm role (SURVEY §2.7,
ref flink-contrib/flink-storm: FlinkTopologyBuilder wrapping spouts/bolts
as Flink operators).

Spouts and bolts written against the (simplified) Storm programming model
run unchanged as a flink_tpu streaming job:

    builder = TopologyBuilder()
    builder.set_spout("lines", LineSpout())
    builder.set_bolt("split", SplitBolt()).shuffle_grouping("lines")
    builder.set_bolt("count", CountBolt()).fields_grouping("split", 0)
    results = FlinkTopology(builder).execute(env)

Lowering: a spout becomes a Source (next_tuple pull loop), a
shuffle/global-grouped bolt a host flat_map in the pre-keyBy chain, and a
fields-grouped bolt a keyed ProcessFunction over the grouping field —
exactly the operator roles the reference's SpoutWrapper/BoltWrapper give
them.

DAG topologies (round 4, ref flink-storm-examples' multi-input shapes):
multiple spouts, a bolt consuming SEVERAL upstreams (their streams union
before the bolt, the FlinkTopology.createTopology merge), and fan-out
(one component feeding several bolts; every leaf collects its own
output).

Multiple fields-grouped bolts (round 5): a topology whose keyed shape
fits one SPMD job (at most one fields-grouped bolt, single-input bolts
below it — fan-out below the keyed bolt included, via sink branches)
lowers to a single streaming job as before; richer shapes — several
`fieldsGrouping` hops, multi-input bolts below a keyed one — run as a
CHAIN of pipeline stages: stateless
bolts fold on the host between stages and every keyed bolt runs its own
keyed process stage over the mesh, its input materialized from the
previous stage's output. Storm topologies here are finite (next_tuple
returns False at exhaustion), so staged execution is exact: stage k runs
to completion before stage k+1 consumes it, and per-key order is
preserved through each collection. No acking: Flink checkpoints replace
Storm's tuple tracking, as in the reference wrapper.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class BasicSpout:
    """Simplified IRichSpout: open() then next_tuple() until None/[] —
    emit via the collector passed to open."""

    def open(self, collector: "SpoutCollector"):
        pass

    def next_tuple(self) -> bool:
        """Emit zero or more tuples via the collector; return False when
        exhausted (finite topologies run to completion)."""
        raise NotImplementedError

    def close(self):
        pass


class BasicBolt:
    """Simplified IRichBolt: prepare() then execute(tuple) emitting via
    the collector."""

    def prepare(self, collector: "BoltCollector"):
        self.collector = collector

    def execute(self, tup: tuple):
        raise NotImplementedError

    def close(self):
        pass


class SpoutCollector:
    def __init__(self):
        self.buf: List[tuple] = []

    def emit(self, tup):
        self.buf.append(tuple(tup))


class BoltCollector(SpoutCollector):
    pass


class _BoltDecl:
    def __init__(self, name: str, bolt: BasicBolt):
        self.name = name
        self.bolt = bolt
        # per-edge groupings: [(upstream, kind, field)] — a bolt may
        # subscribe to several components (ref InputDeclarer chaining)
        self.inputs: List[Tuple[str, str, Any]] = []

    def shuffle_grouping(self, upstream: str) -> "_BoltDecl":
        self.inputs.append((upstream, "shuffle", None))
        return self

    def global_grouping(self, upstream: str) -> "_BoltDecl":
        self.inputs.append((upstream, "global", None))
        return self

    def fields_grouping(self, upstream: str, field) -> "_BoltDecl":
        """field: tuple POSITION (int). The simplified model carries
        positional tuples, not named fields — a string name cannot be
        resolved and must not silently key by the whole tuple."""
        if not isinstance(field, int):
            raise TypeError(
                f"fields_grouping takes a tuple position (int), got "
                f"{field!r}; declare emissions positionally"
            )
        self.inputs.append((upstream, "fields", field))
        return self


class TopologyBuilder:
    """ref TopologyBuilder.setSpout/setBolt."""

    def __init__(self):
        self.spouts: Dict[str, BasicSpout] = {}
        self.bolts: Dict[str, _BoltDecl] = {}

    def set_spout(self, name: str, spout: BasicSpout):
        if name in self.spouts or name in self.bolts:
            raise ValueError(f"duplicate component id {name!r}")
        self.spouts[name] = spout
        return self

    def set_bolt(self, name: str, bolt: BasicBolt) -> _BoltDecl:
        if name in self.bolts or name in self.spouts:
            raise ValueError(f"duplicate component id {name!r}")
        decl = _BoltDecl(name, bolt)
        self.bolts[name] = decl
        return decl


def _keyed_bolt_fn(bolt: BasicBolt):
    """Wrap a bolt as a keyed ProcessFunction (lowered per-key stage)."""
    from flink_tpu.datastream.functions import ProcessFunction

    class _KeyedBolt(ProcessFunction):
        def __init__(self, b):
            self._b = b
            self._coll = BoltCollector()
            self._prepared = False

        def process_element(self, value, ctx, out):
            if not self._prepared:
                self._b.prepare(self._coll)
                self._prepared = True
            self._coll.buf = []
            self._b.execute(tuple(value))
            for t in self._coll.buf:
                out.collect(t)

    return _KeyedBolt(bolt)


def _bolt_flat_map(bolt: BasicBolt):
    state = {"prepared": False}
    coll = BoltCollector()

    def fm(tup):
        if not state["prepared"]:
            bolt.prepare(coll)
            state["prepared"] = True
        coll.buf = []
        bolt.execute(tuple(tup) if isinstance(tup, (tuple, list))
                     else (tup,))
        return list(coll.buf)

    return fm


class FlinkTopology:
    """ref FlinkTopology.createTopology + LocalCluster.submitTopology:
    lowers the declared topology onto the DataStream API and executes."""

    def __init__(self, builder: TopologyBuilder):
        if not builder.spouts:
            raise ValueError("topology needs at least one spout")
        self.builder = builder

    def _topo_order(self) -> List[_BoltDecl]:
        """Topological order of the bolt DAG; validates connectivity,
        acyclicity, and the one-keyed-stage constraint."""
        b = self.builder
        for d in b.bolts.values():
            if not d.inputs:
                raise ValueError(f"bolt {d.name!r} has no grouping")
            for up, _k, _f in d.inputs:
                if up not in b.spouts and up not in b.bolts:
                    raise ValueError(
                        f"bolt {d.name!r} subscribes to unknown "
                        f"component {up!r}"
                    )
        order: List[_BoltDecl] = []
        done = set(b.spouts)
        remaining = dict(b.bolts)
        while remaining:
            ready = [
                d for d in remaining.values()
                if all(up in done for up, _k, _f in d.inputs)
            ]
            if not ready:
                raise ValueError("topology contains a cycle")
            for d in sorted(ready, key=lambda d: d.name):
                order.append(d)
                done.add(d.name)
                del remaining[d.name]
        keyed = [d for d in order if any(k == "fields" for _u, k, _f
                                         in d.inputs)]
        for d in keyed:
            # consistency validated HERE, before execute() touches the
            # env: a failure mid-lowering would leave orphan sources
            kinds = {k for _u, k, _f in d.inputs}
            fields = {f for _u, k, f in d.inputs if k == "fields"}
            if kinds != {"fields"} or len(fields) != 1:
                raise ValueError(
                    f"bolt {d.name!r}: every subscription of a fields-"
                    f"grouped bolt must use fields grouping on the same "
                    f"field position"
                )
        return order

    def _single_job_ok(self, order: List[_BoltDecl]) -> bool:
        """One streaming job covers: at most one fields-grouped bolt,
        linear stateless chain below it (the SPMD executor's shape).
        Everything else goes through the staged path."""
        keyed = [d for d in order if any(k == "fields" for _u, k, _f
                                         in d.inputs)]
        if len(keyed) > 1:
            return False
        if keyed:
            below = {keyed[0].name}
            for d in order:
                ups = {u for u, _k, _f in d.inputs}
                if ups & below:
                    if len(d.inputs) > 1:
                        return False
                    below.add(d.name)
        return True

    def execute(self, env, job_name: str = "storm-topology"):
        """Run to completion. Returns the collected tuples of the single
        leaf component, or {leaf_name: tuples} when the DAG fans out to
        several leaves. Topologies whose keyed shape exceeds one SPMD job
        (several fields-grouped bolts, fan-out below one) run as a chain
        of pipeline stages — see module docstring."""
        order = self._topo_order()   # validate before touching the env
        if not self._single_job_ok(order):
            return self._execute_staged(env, order, job_name)
        return self._execute_single(env, order, job_name)

    def _execute_single(self, env, order, job_name):
        from flink_tpu.runtime.sinks import CollectSink
        from flink_tpu.runtime.sources import Source

        builder = self.builder

        class _SpoutSource(Source):
            def __init__(self, spout):
                self.spout = spout
                self.collector = SpoutCollector()
                self._opened = False
                self._done = False

            def open(self):
                if not self._opened:
                    self.spout.open(self.collector)
                    self._opened = True

            def poll(self, max_records: int):
                out = []
                while len(out) < max_records and not self._done:
                    self.collector.buf = []
                    alive = self.spout.next_tuple()
                    out.extend(self.collector.buf)
                    if not alive:
                        self._done = True
                return out, self._done

            def snapshot_offsets(self):
                return None

            def restore_offsets(self, state):
                pass

        streams = {
            name: env.add_source(_SpoutSource(spout))
            for name, spout in builder.spouts.items()
        }

        for decl in order:
            ups = [streams[u] for u, _k, _f in decl.inputs]
            # multiple subscriptions union into one input stream (the
            # reference unions the input DataStreams in createTopology)
            stream = ups[0].union(*ups[1:]) if len(ups) > 1 else ups[0]
            kinds = {k for _u, k, _f in decl.inputs}
            if kinds <= {"shuffle", "global"}:
                streams[decl.name] = stream.flat_map(
                    _bolt_flat_map(decl.bolt)
                )
                continue
            # consistency already validated by _topo_order
            fields = {f for _u, k, f in decl.inputs if k == "fields"}
            f = fields.pop()
            streams[decl.name] = stream.key_by(
                lambda t, _f=f: t[_f]
            ).process(_keyed_bolt_fn(decl.bolt))

        consumed = {u for d in order for u, _k, _f in d.inputs}
        leaves = [n for n in streams if n not in consumed]
        sinks = {}
        for n in leaves:
            sinks[n] = CollectSink()
            streams[n].add_sink(sinks[n])
        env.execute(job_name)
        for spout in builder.spouts.values():
            spout.close()
        for d in order:
            d.bolt.close()
        if len(leaves) == 1:
            return sinks[leaves[0]].results
        return {n: s.results for n, s in sinks.items()}

    # -- staged execution (round 5: several fields-grouped hops) ---------
    @staticmethod
    def _fresh_env(env):
        """A stage env sharing the job's configuration knobs (each keyed
        stage is its own pipeline execution)."""
        cls = type(env)
        stage = cls(getattr(env, "config", None))
        for attr in ("parallelism", "max_parallelism", "batch_size",
                     "state_capacity_per_shard"):
            if hasattr(env, attr):
                setattr(stage, attr, getattr(env, attr))
        # stages must NOT share the job's checkpoint directory: each is a
        # finite batch whose failure story is re-running the stage from
        # its materialized input, and a shared dir would let stage k+1
        # restore stage k's operator state
        stage.checkpoint_interval_steps = 0
        stage.checkpoint_dir = None
        return stage

    def _execute_staged(self, env, order, job_name):
        """Chain of pipeline stages: spouts drain on the host, stateless
        bolts fold between stages, every fields-grouped bolt runs its own
        keyed process stage over the mesh on the materialized output of
        the previous stage. Exact for finite topologies (the only kind
        this compat layer runs): stage k completes before stage k+1
        consumes it, preserving per-key order through each collection."""
        from flink_tpu.runtime.sinks import CollectSink

        builder = self.builder
        outputs: Dict[str, List[tuple]] = {}
        for name, spout in builder.spouts.items():
            coll = SpoutCollector()
            spout.open(coll)
            tuples: List[tuple] = []
            alive = True
            while alive:
                coll.buf = []
                alive = spout.next_tuple()
                tuples.extend(coll.buf)
            outputs[name] = tuples

        seg = 0
        for decl in order:
            ins: List[tuple] = []
            for u, _k, _f in decl.inputs:
                ins.extend(outputs[u])
            kinds = {k for _u, k, _f in decl.inputs}
            if kinds <= {"shuffle", "global"}:
                fm = _bolt_flat_map(decl.bolt)
                out: List[tuple] = []
                for t in ins:
                    out.extend(fm(t))
                outputs[decl.name] = out
                continue
            f = next(f for _u, k, f in decl.inputs if k == "fields")
            seg += 1
            stage_env = self._fresh_env(env)
            sink = CollectSink()
            (
                stage_env.from_collection(ins)
                .key_by(lambda t, _f=f: t[_f])
                .process(_keyed_bolt_fn(decl.bolt))
                .add_sink(sink)
            )
            stage_env.execute(f"{job_name}-stage{seg}-{decl.name}")
            outputs[decl.name] = list(sink.results)

        for spout in builder.spouts.values():
            spout.close()
        for d in order:
            d.bolt.close()
        consumed = {u for d in order for u, _k, _f in d.inputs}
        leaves = [n for n in outputs if n not in consumed]
        if len(leaves) == 1:
            return outputs[leaves[0]]
        return {n: outputs[n] for n in leaves}
