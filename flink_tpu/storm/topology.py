"""Storm topology compatibility — the flink-storm role (SURVEY §2.7,
ref flink-contrib/flink-storm: FlinkTopologyBuilder wrapping spouts/bolts
as Flink operators).

Spouts and bolts written against the (simplified) Storm programming model
run unchanged as a flink_tpu streaming job:

    builder = TopologyBuilder()
    builder.set_spout("lines", LineSpout())
    builder.set_bolt("split", SplitBolt()).shuffle_grouping("lines")
    builder.set_bolt("count", CountBolt()).fields_grouping("split", 0)
    results = FlinkTopology(builder).execute(env)

Lowering: a spout becomes a Source (next_tuple pull loop), a
shuffle/global-grouped bolt a host flat_map in the pre-keyBy chain, and a
fields-grouped bolt a keyed ProcessFunction over the grouping field —
exactly the operator roles the reference's SpoutWrapper/BoltWrapper give
them.

DAG topologies (round 4, ref flink-storm-examples' multi-input shapes):
multiple spouts, a bolt consuming SEVERAL upstreams (their streams union
before the bolt, the FlinkTopology.createTopology merge), and fan-out
(one component feeding several bolts; every leaf collects its own
output). At most one fields-grouped bolt per topology, with a linear
chain below it (one keyed stage per job — the SPMD executor's shape);
richer keyed DAGs belong on the native DataStream API. No acking: Flink
checkpoints replace Storm's tuple tracking, as in the reference wrapper.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class BasicSpout:
    """Simplified IRichSpout: open() then next_tuple() until None/[] —
    emit via the collector passed to open."""

    def open(self, collector: "SpoutCollector"):
        pass

    def next_tuple(self) -> bool:
        """Emit zero or more tuples via the collector; return False when
        exhausted (finite topologies run to completion)."""
        raise NotImplementedError

    def close(self):
        pass


class BasicBolt:
    """Simplified IRichBolt: prepare() then execute(tuple) emitting via
    the collector."""

    def prepare(self, collector: "BoltCollector"):
        self.collector = collector

    def execute(self, tup: tuple):
        raise NotImplementedError

    def close(self):
        pass


class SpoutCollector:
    def __init__(self):
        self.buf: List[tuple] = []

    def emit(self, tup):
        self.buf.append(tuple(tup))


class BoltCollector(SpoutCollector):
    pass


class _BoltDecl:
    def __init__(self, name: str, bolt: BasicBolt):
        self.name = name
        self.bolt = bolt
        # per-edge groupings: [(upstream, kind, field)] — a bolt may
        # subscribe to several components (ref InputDeclarer chaining)
        self.inputs: List[Tuple[str, str, Any]] = []

    def shuffle_grouping(self, upstream: str) -> "_BoltDecl":
        self.inputs.append((upstream, "shuffle", None))
        return self

    def global_grouping(self, upstream: str) -> "_BoltDecl":
        self.inputs.append((upstream, "global", None))
        return self

    def fields_grouping(self, upstream: str, field) -> "_BoltDecl":
        """field: tuple POSITION (int). The simplified model carries
        positional tuples, not named fields — a string name cannot be
        resolved and must not silently key by the whole tuple."""
        if not isinstance(field, int):
            raise TypeError(
                f"fields_grouping takes a tuple position (int), got "
                f"{field!r}; declare emissions positionally"
            )
        self.inputs.append((upstream, "fields", field))
        return self


class TopologyBuilder:
    """ref TopologyBuilder.setSpout/setBolt."""

    def __init__(self):
        self.spouts: Dict[str, BasicSpout] = {}
        self.bolts: Dict[str, _BoltDecl] = {}

    def set_spout(self, name: str, spout: BasicSpout):
        if name in self.spouts or name in self.bolts:
            raise ValueError(f"duplicate component id {name!r}")
        self.spouts[name] = spout
        return self

    def set_bolt(self, name: str, bolt: BasicBolt) -> _BoltDecl:
        if name in self.bolts or name in self.spouts:
            raise ValueError(f"duplicate component id {name!r}")
        decl = _BoltDecl(name, bolt)
        self.bolts[name] = decl
        return decl


def _bolt_flat_map(bolt: BasicBolt):
    state = {"prepared": False}
    coll = BoltCollector()

    def fm(tup):
        if not state["prepared"]:
            bolt.prepare(coll)
            state["prepared"] = True
        coll.buf = []
        bolt.execute(tuple(tup) if isinstance(tup, (tuple, list))
                     else (tup,))
        return list(coll.buf)

    return fm


class FlinkTopology:
    """ref FlinkTopology.createTopology + LocalCluster.submitTopology:
    lowers the declared topology onto the DataStream API and executes."""

    def __init__(self, builder: TopologyBuilder):
        if not builder.spouts:
            raise ValueError("topology needs at least one spout")
        self.builder = builder

    def _topo_order(self) -> List[_BoltDecl]:
        """Topological order of the bolt DAG; validates connectivity,
        acyclicity, and the one-keyed-stage constraint."""
        b = self.builder
        for d in b.bolts.values():
            if not d.inputs:
                raise ValueError(f"bolt {d.name!r} has no grouping")
            for up, _k, _f in d.inputs:
                if up not in b.spouts and up not in b.bolts:
                    raise ValueError(
                        f"bolt {d.name!r} subscribes to unknown "
                        f"component {up!r}"
                    )
        order: List[_BoltDecl] = []
        done = set(b.spouts)
        remaining = dict(b.bolts)
        while remaining:
            ready = [
                d for d in remaining.values()
                if all(up in done for up, _k, _f in d.inputs)
            ]
            if not ready:
                raise ValueError("topology contains a cycle")
            for d in sorted(ready, key=lambda d: d.name):
                order.append(d)
                done.add(d.name)
                del remaining[d.name]
        keyed = [d for d in order if any(k == "fields" for _u, k, _f
                                         in d.inputs)]
        for d in keyed:
            # consistency validated HERE, before execute() touches the
            # env: a failure mid-lowering would leave orphan sources
            kinds = {k for _u, k, _f in d.inputs}
            fields = {f for _u, k, f in d.inputs if k == "fields"}
            if kinds != {"fields"} or len(fields) != 1:
                raise ValueError(
                    f"bolt {d.name!r}: every subscription of a fields-"
                    f"grouped bolt must use fields grouping on the same "
                    f"field position"
                )
        if len(keyed) > 1:
            raise ValueError(
                "at most one fields-grouped bolt per topology (one keyed "
                "stage per job); use the DataStream API for richer shapes"
            )
        if keyed:
            # everything downstream of the keyed bolt must be linear
            kname = keyed[0].name
            below = {kname}
            for d in order:
                ups = {u for u, _k, _f in d.inputs}
                if ups & below:
                    if len(d.inputs) > 1:
                        raise ValueError(
                            "the chain below a fields-grouped bolt must "
                            "be linear (single-input bolts)"
                        )
                    below.add(d.name)
        return order

    def execute(self, env, job_name: str = "storm-topology"):
        """Run to completion. Returns the collected tuples of the single
        leaf component, or {leaf_name: tuples} when the DAG fans out to
        several leaves."""
        from flink_tpu.datastream.functions import ProcessFunction
        from flink_tpu.runtime.sinks import CollectSink
        from flink_tpu.runtime.sources import Source

        order = self._topo_order()   # validate before touching the env
        builder = self.builder

        class _SpoutSource(Source):
            def __init__(self, spout):
                self.spout = spout
                self.collector = SpoutCollector()
                self._opened = False
                self._done = False

            def open(self):
                if not self._opened:
                    self.spout.open(self.collector)
                    self._opened = True

            def poll(self, max_records: int):
                out = []
                while len(out) < max_records and not self._done:
                    self.collector.buf = []
                    alive = self.spout.next_tuple()
                    out.extend(self.collector.buf)
                    if not alive:
                        self._done = True
                return out, self._done

            def snapshot_offsets(self):
                return None

            def restore_offsets(self, state):
                pass

        streams = {
            name: env.add_source(_SpoutSource(spout))
            for name, spout in builder.spouts.items()
        }

        for decl in order:
            ups = [streams[u] for u, _k, _f in decl.inputs]
            # multiple subscriptions union into one input stream (the
            # reference unions the input DataStreams in createTopology)
            stream = ups[0].union(*ups[1:]) if len(ups) > 1 else ups[0]
            kinds = {k for _u, k, _f in decl.inputs}
            if kinds <= {"shuffle", "global"}:
                streams[decl.name] = stream.flat_map(
                    _bolt_flat_map(decl.bolt)
                )
                continue
            # consistency already validated by _topo_order
            fields = {f for _u, k, f in decl.inputs if k == "fields"}
            bolt = decl.bolt

            class _KeyedBolt(ProcessFunction):
                def __init__(self, b):
                    self._b = b
                    self._coll = BoltCollector()
                    self._prepared = False

                def process_element(self, value, ctx, out):
                    if not self._prepared:
                        self._b.prepare(self._coll)
                        self._prepared = True
                    self._coll.buf = []
                    self._b.execute(tuple(value))
                    for t in self._coll.buf:
                        out.collect(t)

            f = fields.pop()
            streams[decl.name] = stream.key_by(
                lambda t, _f=f: t[_f]
            ).process(_KeyedBolt(bolt))

        consumed = {u for d in order for u, _k, _f in d.inputs}
        leaves = [n for n in streams if n not in consumed]
        sinks = {}
        for n in leaves:
            sinks[n] = CollectSink()
            streams[n].add_sink(sinks[n])
        env.execute(job_name)
        for spout in builder.spouts.values():
            spout.close()
        for d in order:
            d.bolt.close()
        if len(leaves) == 1:
            return sinks[leaves[0]].results
        return {n: s.results for n, s in sinks.items()}
