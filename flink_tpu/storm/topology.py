"""Storm topology compatibility — the flink-storm role (SURVEY §2.7,
ref flink-contrib/flink-storm: FlinkTopologyBuilder wrapping spouts/bolts
as Flink operators).

Spouts and bolts written against the (simplified) Storm programming model
run unchanged as a flink_tpu streaming job:

    builder = TopologyBuilder()
    builder.set_spout("lines", LineSpout())
    builder.set_bolt("split", SplitBolt()).shuffle_grouping("lines")
    builder.set_bolt("count", CountBolt()).fields_grouping("split", 0)
    results = FlinkTopology(builder).execute(env)

Lowering: a spout becomes a Source (next_tuple pull loop), a
shuffle/global-grouped bolt a host flat_map in the pre-keyBy chain, and a
fields-grouped bolt a keyed ProcessFunction over the grouping field —
exactly the operator roles the reference's SpoutWrapper/BoltWrapper give
them. Linear topologies (each bolt one upstream), the shape the
reference's examples use; no acking (Flink checkpoints replace Storm's
tuple tracking, as in the reference wrapper).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple


class BasicSpout:
    """Simplified IRichSpout: open() then next_tuple() until None/[] —
    emit via the collector passed to open."""

    def open(self, collector: "SpoutCollector"):
        pass

    def next_tuple(self) -> bool:
        """Emit zero or more tuples via the collector; return False when
        exhausted (finite topologies run to completion)."""
        raise NotImplementedError

    def close(self):
        pass


class BasicBolt:
    """Simplified IRichBolt: prepare() then execute(tuple) emitting via
    the collector."""

    def prepare(self, collector: "BoltCollector"):
        self.collector = collector

    def execute(self, tup: tuple):
        raise NotImplementedError

    def close(self):
        pass


class SpoutCollector:
    def __init__(self):
        self.buf: List[tuple] = []

    def emit(self, tup):
        self.buf.append(tuple(tup))


class BoltCollector(SpoutCollector):
    pass


class _BoltDecl:
    def __init__(self, name: str, bolt: BasicBolt):
        self.name = name
        self.bolt = bolt
        self.upstream: Optional[str] = None
        self.grouping: Optional[Tuple[str, Any]] = None

    def shuffle_grouping(self, upstream: str) -> "_BoltDecl":
        self.upstream = upstream
        self.grouping = ("shuffle", None)
        return self

    def global_grouping(self, upstream: str) -> "_BoltDecl":
        self.upstream = upstream
        self.grouping = ("global", None)
        return self

    def fields_grouping(self, upstream: str, field) -> "_BoltDecl":
        """field: tuple POSITION (int). The simplified model carries
        positional tuples, not named fields — a string name cannot be
        resolved and must not silently key by the whole tuple."""
        if not isinstance(field, int):
            raise TypeError(
                f"fields_grouping takes a tuple position (int), got "
                f"{field!r}; declare emissions positionally"
            )
        self.upstream = upstream
        self.grouping = ("fields", field)
        return self


class TopologyBuilder:
    """ref TopologyBuilder.setSpout/setBolt."""

    def __init__(self):
        self.spout_name: Optional[str] = None
        self.spout: Optional[BasicSpout] = None
        self.bolts: Dict[str, _BoltDecl] = {}

    def set_spout(self, name: str, spout: BasicSpout):
        if self.spout is not None:
            raise ValueError("one spout per topology (linear topologies)")
        self.spout_name, self.spout = name, spout
        return self

    def set_bolt(self, name: str, bolt: BasicBolt) -> _BoltDecl:
        if name in self.bolts or name == self.spout_name:
            raise ValueError(f"duplicate component id {name!r}")
        decl = _BoltDecl(name, bolt)
        self.bolts[name] = decl
        return decl


class FlinkTopology:
    """ref FlinkTopology.createTopology + LocalCluster.submitTopology:
    lowers the declared topology onto the DataStream API and executes."""

    def __init__(self, builder: TopologyBuilder):
        if builder.spout is None:
            raise ValueError("topology needs a spout")
        self.builder = builder

    def _chain_order(self) -> List[_BoltDecl]:
        """Topological order of the linear chain from the spout."""
        by_upstream = {}
        for d in self.builder.bolts.values():
            if d.upstream is None:
                raise ValueError(f"bolt {d.name!r} has no grouping")
            if d.upstream in by_upstream:
                raise ValueError("linear topologies only (one consumer "
                                 "per component)")
            by_upstream[d.upstream] = d
        chain, cur = [], self.builder.spout_name
        while cur in by_upstream:
            chain.append(by_upstream[cur])
            cur = by_upstream[cur].name
        if len(chain) != len(self.builder.bolts):
            raise ValueError("disconnected bolts in topology")
        return chain

    def execute(self, env, job_name: str = "storm-topology"):
        """Run to completion; returns the collected output tuples of the
        last component."""
        from flink_tpu.datastream.functions import ProcessFunction
        from flink_tpu.runtime.sinks import CollectSink
        from flink_tpu.runtime.sources import Source

        chain = self._chain_order()   # validate before touching the env
        builder = self.builder

        class _SpoutSource(Source):
            def __init__(self):
                self.collector = SpoutCollector()
                self._opened = False
                self._done = False

            def open(self):
                if not self._opened:
                    builder.spout.open(self.collector)
                    self._opened = True

            def poll(self, max_records: int):
                out = []
                while len(out) < max_records and not self._done:
                    self.collector.buf = []
                    alive = builder.spout.next_tuple()
                    out.extend(self.collector.buf)
                    if not alive:
                        self._done = True
                return out, self._done

            def snapshot_offsets(self):
                return None

            def restore_offsets(self, state):
                pass

        stream = env.add_source(_SpoutSource())

        def bolt_flat_map(bolt: BasicBolt):
            state = {"prepared": False}
            coll = BoltCollector()
            bolt_ref = bolt

            def fm(tup):
                if not state["prepared"]:
                    bolt_ref.prepare(coll)
                    state["prepared"] = True
                coll.buf = []
                bolt_ref.execute(tuple(tup) if isinstance(tup, (tuple, list))
                                 else (tup,))
                return list(coll.buf)

            return fm

        sink = CollectSink()
        i = 0
        while i < len(chain):
            decl = chain[i]
            kind, field = decl.grouping
            if kind in ("shuffle", "global"):
                # operator chaining, like the reference wrapping the bolt
                # as a chained flatMap
                stream = stream.flat_map(bolt_flat_map(decl.bolt))
                i += 1
                continue
            # fields grouping: keyed execution of THIS bolt
            bolt = decl.bolt

            class _KeyedBolt(ProcessFunction):
                def __init__(self, b):
                    self._b = b
                    self._coll = BoltCollector()
                    self._prepared = False

                def process_element(self, value, ctx, out):
                    if not self._prepared:
                        self._b.prepare(self._coll)
                        self._prepared = True
                    self._coll.buf = []
                    self._b.execute(tuple(value))
                    for t in self._coll.buf:
                        out.collect(t)

            f = field
            stream = stream.key_by(
                lambda t, _f=f: t[_f]
            ).process(_KeyedBolt(bolt))
            i += 1
        stream.add_sink(sink)
        job = env.execute(job_name)
        builder.spout.close()
        for d in chain:
            d.bolt.close()
        return sink.results
