"""CLI — run/list/info/cancel/stop/savepoint (ref CliFrontend.java:109,
actions at :114-119, SURVEY §2.9).

    python -m flink_tpu.cli run [-s SAVEPOINT] script.py [args...]
    python -m flink_tpu.cli list          -m HOST:PORT
    python -m flink_tpu.cli info  JOB_ID  -m HOST:PORT
    python -m flink_tpu.cli cancel JOB_ID -m HOST:PORT
    python -m flink_tpu.cli stop   JOB_ID -m HOST:PORT
    python -m flink_tpu.cli savepoint JOB_ID TARGET_DIR -m HOST:PORT

`run` executes the user program in-process (PackagedProgram role): the
script builds pipelines with StreamExecutionEnvironment and calls execute();
FLINK_TPU_SAVEPOINT is exported for `-s` so programs can pass it as
execute(restore_from=...) — or use cli.restore_path() to read it.
The other actions talk to a MiniCluster control server (JobManager RPC
analog) started by a long-running program via
MiniCluster.start_control_server().
"""

from __future__ import annotations

import argparse
import json
import os
import runpy
import sys

from flink_tpu.runtime.cluster import control_request


def restore_path():
    """The -s/--fromSavepoint path for the current `run`, if any."""
    return os.environ.get("FLINK_TPU_SAVEPOINT") or None


# ref jobmanager.rpc.port default (flink-conf.yaml:33); overridable via
# controller.rpc.port in conf/flink-tpu-conf.yaml ($FLINK_TPU_CONF_DIR)
def _default_port() -> int:
    from flink_tpu.core.config import load_global_configuration

    return load_global_configuration().get_int("controller.rpc.port", 6123)


DEFAULT_PORT = 6123


def _addr(spec: str):
    if ":" not in spec:  # bare hostname
        return spec or "127.0.0.1", _default_port()
    host, _, port = spec.rpartition(":")
    host = host or "127.0.0.1"
    if not port:
        return host, _default_port()
    try:
        return host, int(port)
    except ValueError:
        raise SystemExit(
            f"invalid --jobmanager address {spec!r} (expected HOST:PORT)"
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flink-tpu")
    sub = ap.add_subparsers(dest="action", required=True)

    p_run = sub.add_parser("run", help="execute a job program")
    p_run.add_argument("-s", "--fromSavepoint", default=None)
    p_run.add_argument("script")
    p_run.add_argument("args", nargs=argparse.REMAINDER)

    for name in ("list",):
        p = sub.add_parser(name)
        p.add_argument("-m", "--jobmanager", default="127.0.0.1:6123")
    for name in ("info", "cancel", "stop"):
        p = sub.add_parser(name)
        p.add_argument("job_id")
        p.add_argument("-m", "--jobmanager", default="127.0.0.1:6123")
    p_sp = sub.add_parser("savepoint")
    p_sp.add_argument("job_id")
    p_sp.add_argument("target")
    p_sp.add_argument("-m", "--jobmanager", default="127.0.0.1:6123")

    ns = ap.parse_args(argv)

    if ns.action == "run":
        if ns.fromSavepoint:
            os.environ["FLINK_TPU_SAVEPOINT"] = ns.fromSavepoint
        sys.argv = [ns.script] + ns.args
        runpy.run_path(ns.script, run_name="__main__")
        return 0

    host, port = _addr(ns.jobmanager)
    if ns.action == "list":
        req = {"action": "list"}
    elif ns.action == "savepoint":
        req = {"action": "savepoint", "job_id": ns.job_id, "path": ns.target}
    else:
        req = {"action": ns.action, "job_id": ns.job_id}
    resp = control_request(host, port, req)
    print(json.dumps(resp, indent=2, default=str))
    return 0 if resp.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
