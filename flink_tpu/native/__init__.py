"""Native runtime layer — C++ ring-buffer ingestion, columnar record codec,
and host spill store, bound via ctypes (SURVEY §2.10: the reference's
Unsafe/Netty/RocksDB native surface, rebuilt for this runtime).

The shared library compiles on first use (g++ -O2, ~1s) and is cached next
to the sources; set FLINK_TPU_NATIVE_REBUILD=1 to force a rebuild.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import zlib
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_SO = os.path.join(_DIR, "_flink_tpu_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None

RECORD_BYTES = 20  # u64 key | i64 ts_ms | f32 value


def _build() -> str:
    srcs = [os.path.join(_SRC, f)
            for f in ("ringbuf.cpp", "spillstore.cpp", "textparse.cpp")]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if (
        os.path.exists(_SO)
        and os.path.getmtime(_SO) > newest_src
        and not os.environ.get("FLINK_TPU_NATIVE_REBUILD")
    ):
        return _SO
    tmp = _SO + ".tmp"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, *srcs, "-lrt",
    ]
    subprocess.run(cmd, check=True, capture_output=True)
    os.replace(tmp, _SO)
    return _SO


def get_lib() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            try:
                lib = ctypes.CDLL(_build())
            except OSError:
                # a stale/foreign-platform cached binary: force a rebuild
                if os.path.exists(_SO):
                    os.remove(_SO)
                lib = ctypes.CDLL(_build())
            u8p = ctypes.POINTER(ctypes.c_uint8)
            u64p = ctypes.POINTER(ctypes.c_uint64)
            i64p = ctypes.POINTER(ctypes.c_int64)
            f32p = ctypes.POINTER(ctypes.c_float)

            lib.rb_create.restype = ctypes.c_void_p
            lib.rb_create.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
            ]
            lib.rb_destroy.argtypes = [ctypes.c_void_p]
            lib.rb_capacity.restype = ctypes.c_uint64
            lib.rb_capacity.argtypes = [ctypes.c_void_p]
            lib.rb_readable.restype = ctypes.c_uint64
            lib.rb_readable.argtypes = [ctypes.c_void_p]
            lib.rb_write.restype = ctypes.c_int
            lib.rb_write.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint32]
            lib.rb_read.restype = ctypes.c_int64
            lib.rb_read.argtypes = [ctypes.c_void_p, u8p, ctypes.c_uint64]

            lib.records_encode.restype = ctypes.c_int64
            lib.records_encode.argtypes = [
                u64p, i64p, f32p, ctypes.c_uint64, u8p, ctypes.c_uint64,
            ]
            lib.records_decode.restype = ctypes.c_int64
            lib.records_decode.argtypes = [
                u8p, ctypes.c_uint64, u64p, i64p, f32p, ctypes.c_uint64,
            ]

            lib.tp_parse.restype = ctypes.c_int64
            lib.tp_parse.argtypes = [
                u8p, ctypes.c_int64, i64p, u64p, i64p,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, i64p,
            ]

            lib.spill_create.restype = ctypes.c_void_p
            lib.spill_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
            lib.spill_destroy.argtypes = [ctypes.c_void_p]
            lib.spill_count.restype = ctypes.c_uint64
            lib.spill_count.argtypes = [ctypes.c_void_p]
            lib.spill_capacity.restype = ctypes.c_uint64
            lib.spill_capacity.argtypes = [ctypes.c_void_p]
            lib.spill_width.restype = ctypes.c_uint64
            lib.spill_width.argtypes = [ctypes.c_void_p]
            lib.spill_put_batch.argtypes = [
                ctypes.c_void_p, u64p, f32p, ctypes.c_uint64,
            ]
            lib.spill_get_batch.argtypes = [
                ctypes.c_void_p, u64p, f32p, u8p, ctypes.c_uint64,
            ]
            lib.spill_delete_batch.restype = ctypes.c_uint64
            lib.spill_delete_batch.argtypes = [
                ctypes.c_void_p, u64p, ctypes.c_uint64,
            ]
            lib.spill_dump.restype = ctypes.c_uint64
            lib.spill_dump.argtypes = [
                ctypes.c_void_p, u64p, f32p, ctypes.c_uint64,
            ]
            lib.spill_save.restype = ctypes.c_int
            lib.spill_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
            lib.spill_load.restype = ctypes.c_void_p
            lib.spill_load.argtypes = [ctypes.c_char_p]
            _lib = lib
    return _lib


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class RingBuffer:
    """SPSC ingestion ring (process-private, or named POSIX shm when `name`
    is given — the cross-process DCN ingestion seam)."""

    def __init__(self, capacity: int = 1 << 22, name: Optional[str] = None,
                 create=True):
        """create: True = owner create (resets even a stale segment),
        False = attach to an existing initialized segment,
        "exclusive" = create only if absent (fails if the name exists —
        the race-safe attach-or-create probe)."""
        self._lib = get_lib()
        mode = 2 if create == "exclusive" else int(bool(create))
        self._h = self._lib.rb_create(
            name.encode() if name else None, capacity, mode
        )
        if not self._h:
            raise OSError(f"ring buffer create failed (name={name!r})")
        # when attaching, the creator's capacity governs (read from the
        # shared header) — size the scratch buffer from the real value
        self._scratch = np.empty(int(self._lib.rb_capacity(self._h)),
                                 np.uint8)

    def close(self):
        if self._h:
            self._lib.rb_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @property
    def readable_bytes(self) -> int:
        return int(self._lib.rb_readable(self._h))

    def write_bytes(self, payload: bytes) -> bool:
        if not payload:
            return True  # nothing to enqueue
        buf = np.frombuffer(payload, np.uint8)
        return bool(self._lib.rb_write(self._h, _u8(buf), len(buf)))

    def write_records(self, keys, ts_ms, values) -> bool:
        """Columnar producer: encode + frame one batch; False = ring full
        (backpressure)."""
        keys = np.ascontiguousarray(keys, np.uint64)
        ts_ms = np.ascontiguousarray(ts_ms, np.int64)
        values = np.ascontiguousarray(values, np.float32)
        n = len(keys)
        if n == 0:
            return True
        out = np.empty(n * RECORD_BYTES, np.uint8)
        wrote = self._lib.records_encode(
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ts_ms.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n, _u8(out), len(out),
        )
        if wrote < 0:
            raise ValueError("encode overflow")
        return bool(self._lib.rb_write(self._h, _u8(out), int(wrote)))

    def read_batch(self) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Drain one framed batch into columnar arrays; None when empty."""
        got = self._lib.rb_read(self._h, _u8(self._scratch),
                                len(self._scratch))
        if got == 0:
            return None
        if got < 0:
            raise BufferError("batch larger than scratch buffer")
        n = int(got) // RECORD_BYTES
        keys = np.empty(n, np.uint64)
        ts = np.empty(n, np.int64)
        vals = np.empty(n, np.float32)
        dec = self._lib.records_decode(
            _u8(self._scratch), int(got),
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
        )
        if dec < 0:
            raise ValueError("frame corrupt (length not record-aligned)")
        return keys, ts, vals


class SpillStore:
    """Host overflow tier for keyed state (the RocksDB seam): batch
    put/get/delete of (u64 key -> float[width] block), save/load files."""

    def __init__(self, width: int = 1, initial_capacity: int = 1024,
                 _handle=None):
        self._lib = get_lib()
        self._h = (
            _handle if _handle is not None
            else self._lib.spill_create(initial_capacity, width)
        )
        self.width = int(self._lib.spill_width(self._h))

    def close(self):
        if self._h:
            self._lib.spill_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __len__(self) -> int:
        return int(self._lib.spill_count(self._h))

    def put(self, keys, values):
        keys = np.ascontiguousarray(keys, np.uint64)
        values = np.ascontiguousarray(values, np.float32).reshape(
            len(keys), self.width
        )
        self._lib.spill_put_batch(
            self._h,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            len(keys),
        )

    def get(self, keys) -> Tuple[np.ndarray, np.ndarray]:
        keys = np.ascontiguousarray(keys, np.uint64)
        n = len(keys)
        vals = np.empty((n, self.width), np.float32)
        found = np.empty(n, np.uint8)
        self._lib.spill_get_batch(
            self._h,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            _u8(found), n,
        )
        return vals, found.astype(bool)

    def delete(self, keys) -> int:
        keys = np.ascontiguousarray(keys, np.uint64)
        return int(self._lib.spill_delete_batch(
            self._h,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(keys),
        ))

    def dump(self) -> Tuple[np.ndarray, np.ndarray]:
        n = len(self)
        keys = np.empty(n, np.uint64)
        vals = np.empty((n, self.width), np.float32)
        got = self._lib.spill_dump(
            self._h,
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n,
        )
        return keys[:got], vals[:got]

    # checksummed dump file: magic + width/count header, CRC32 over the
    # key and value payload bytes. A torn or bit-flipped spill dump must
    # surface as a clean OSError at load — the caller falls back to
    # re-seeding from the logical snapshot — never as silently wrong
    # window state (the pre-CRC native format restored whatever bytes
    # the file held).
    _SAVE_MAGIC = b"SPL2"

    def save(self, path: str):
        keys, vals = self.dump()
        kb = np.ascontiguousarray(keys, np.uint64).tobytes()
        vb = np.ascontiguousarray(vals, np.float32).tobytes()
        crc = zlib.crc32(kb)
        crc = zlib.crc32(vb, crc)
        header = self._SAVE_MAGIC + np.asarray(
            [self.width, len(keys), crc], np.uint64
        ).tobytes()
        try:
            with open(path, "wb") as f:
                f.write(header)
                f.write(kb)
                f.write(vb)
        except OSError as e:
            raise OSError(f"spill save failed: {path}: {e}") from e

    @classmethod
    def load(cls, path: str) -> "SpillStore":
        from flink_tpu.testing import faults

        faults.inject("ckpt.spill.read", path=path)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise OSError(f"spill load failed: {path}: {e}") from e
        m = len(cls._SAVE_MAGIC)
        if len(blob) < m + 24 or blob[:m] != cls._SAVE_MAGIC:
            raise OSError(f"spill load failed: {path}: bad header")
        width, count, crc = (
            int(v) for v in np.frombuffer(blob[m:m + 24], np.uint64)
        )
        kb_end = m + 24 + count * 8
        vb_end = kb_end + count * width * 4
        if len(blob) != vb_end:
            raise OSError(
                f"spill load failed: {path}: truncated "
                f"({len(blob)} bytes, expected {vb_end})"
            )
        got = zlib.crc32(blob[m + 24:kb_end])
        got = zlib.crc32(blob[kb_end:vb_end], got)
        if got != crc:
            raise OSError(
                f"spill load failed: {path}: checksum mismatch "
                f"(stored {crc:#x}, computed {got:#x})"
            )
        store = cls(width=width, initial_capacity=max(16, count * 2))
        if count:
            store.put(
                np.frombuffer(blob[m + 24:kb_end], np.uint64),
                np.frombuffer(blob[kb_end:vb_end], np.float32).reshape(
                    count, width
                ),
            )
        return store


def parse_ts_words(data, cap: Optional[int] = None
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray, int]:
    """One-pass native parse of newline-delimited "<ts> tok tok ..."
    text (native/src/textparse.cpp — the SocketWindowWordCount split/
    parse/hash done once per batch instead of per line in Python).

    Returns (ts int64[n], ids uint64[n], offsets int64[n],
    lengths int32[n], consumed_bytes). Only complete lines are
    consumed; feed the unconsumed tail back with the next chunk.
    ``cap`` bounds the tokens returned per call (line-atomic: parsing
    stops BEFORE a line that would overflow, so a caller re-offers the
    remainder — the poll-contract seam; a single line wider than cap is
    still returned whole rather than wedging). Token ids are FNV-1a 64
    over the token bytes (stable, checkpoint-safe); offsets/lengths
    index into ``data`` so callers can materialize the strings of
    first-seen ids only.
    """
    lib = get_lib()
    buf = (np.frombuffer(data, np.uint8)
           if isinstance(data, (bytes, bytearray, memoryview))
           else np.ascontiguousarray(data, np.uint8))
    nbytes = len(buf)
    if nbytes == 0:
        return (np.zeros(0, np.int64), np.zeros(0, np.uint64),
                np.zeros(0, np.int64), np.zeros(0, np.int32), 0)
    # a token occupies >= 2 bytes (1 char + separator/newline)
    hard_cap = nbytes // 2 + 1
    use_cap = min(hard_cap, cap) if cap else hard_cap

    def run(c):
        ts = np.empty(c, np.int64)
        ids = np.empty(c, np.uint64)
        offs = np.empty(c, np.int64)
        lens = np.empty(c, np.int32)
        consumed = ctypes.c_int64(0)
        n = lib.tp_parse(
            _u8(buf), nbytes,
            ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            c, ctypes.byref(consumed),
        )
        return ts[:n], ids[:n], offs[:n], lens[:n], consumed.value

    out = run(use_cap)
    if out[4] == 0 and len(out[0]) == 0 and use_cap < hard_cap \
            and 0x0A in buf:
        # one line wider than the requested cap: grow until it fits
        # (never wedge on a pathological line)
        while len(out[0]) == 0 and use_cap < hard_cap:
            use_cap = min(hard_cap, use_cap * 2)
            out = run(use_cap)
    return out
