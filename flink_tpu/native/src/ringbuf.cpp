// Ingestion ring buffer + columnar record codec.
//
// Replaces the reference's native hot path (SURVEY §2.10): where Flink used
// sun.misc.Unsafe MemorySegments + Netty buffers to move serialized records
// (NetworkBufferPool / SpanningRecordSerializer), this is a lock-free SPSC
// ring over POSIX shared memory: a producer (socket reader, Kafka client,
// another process) frames record batches in, the Python executor drains them
// GIL-free, and the fixed wire format parses straight into contiguous
// columnar arrays ready for device upload — no per-record Python objects.
//
// Wire format, one record = 20 bytes little-endian:
//     u64 key_id | i64 ts_ms | f32 value
// Framing in the ring: u32 batch_len | batch bytes.
//
// SPSC memory ordering: producer writes payload THEN publishes head with
// release; consumer reads head with acquire THEN payload. Single producer,
// single consumer (the executor's poll loop), like the reference's
// one-subpartition-one-reader channels.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cerrno>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

struct RingHeader {
  std::atomic<uint64_t> head;  // next write offset (monotonic)
  std::atomic<uint64_t> tail;  // next read offset (monotonic)
  uint64_t capacity;           // data bytes
  uint64_t magic;
};

static const uint64_t RB_MAGIC = 0x464c4e4b54505531ull;  // "FLNKTPU1"

struct Ring {
  RingHeader* hdr;
  uint8_t* data;
  int shm_fd;       // -1 for private memory
  char name[256];
  int owner;
};

static uint64_t ring_total_size(uint64_t capacity) {
  return sizeof(RingHeader) + capacity;
}

// name == nullptr -> process-private (malloc); else POSIX shm for
// cross-process ingestion.
//
// create modes:
//   0 = attach to an existing, fully-initialized segment (magic checked)
//   1 = owner create: reset even a stale pre-existing segment
//   2 = exclusive create: fail with EEXIST if the segment already exists
//       (the attach-or-create caller's probe — can never clobber a live
//       producer's ring)
// Creation is race-safe: the segment is created with O_EXCL and the magic
// word is published LAST with release ordering, so a concurrent attacher
// either sees no segment, an unfinished header (magic mismatch -> retry),
// or a fully initialized ring — never a half-written one it could then
// "repair" by re-creating (the round-1 bug).
Ring* rb_create(const char* name, uint64_t capacity, int create) {
  Ring* r = new Ring();
  r->shm_fd = -1;
  r->owner = create != 0;
  r->name[0] = 0;
  void* mem = nullptr;
  if (name == nullptr) {
    mem = ::malloc(ring_total_size(capacity));
    if (!mem) { delete r; return nullptr; }
  } else {
    std::strncpy(r->name, name, sizeof(r->name) - 1);
    int fd = -1;
    if (create) {
      fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
      if (fd < 0 && errno == EEXIST && create == 1) {
        // owner reset of a stale segment: remove, then recreate
        // exclusively (the owner role is single-writer by contract)
        shm_unlink(name);
        fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
      }
    } else {
      fd = shm_open(name, O_RDWR, 0600);
    }
    if (fd < 0) { delete r; return nullptr; }
    if (create && ftruncate(fd, (off_t)ring_total_size(capacity)) != 0) {
      close(fd); shm_unlink(name); delete r; return nullptr;
    }
    if (!create) {
      // attaching: the CREATOR's capacity governs — read it from the
      // header before mapping the full region, else copy_in/out would
      // index past a too-small mapping
      struct stat st;
      if (fstat(fd, &st) != 0 ||
          (uint64_t)st.st_size < sizeof(RingHeader)) {
        close(fd); delete r; return nullptr;
      }
      void* hmem = mmap(nullptr, sizeof(RingHeader), PROT_READ,
                        MAP_SHARED, fd, 0);
      if (hmem == MAP_FAILED) { close(fd); delete r; return nullptr; }
      RingHeader* h = (RingHeader*)hmem;
      uint64_t magic = __atomic_load_n(&h->magic, __ATOMIC_ACQUIRE);
      uint64_t actual = h->capacity;
      munmap(hmem, sizeof(RingHeader));
      if (magic != RB_MAGIC ||
          (uint64_t)st.st_size < ring_total_size(actual)) {
        close(fd); delete r; return nullptr;
      }
      capacity = actual;
    }
    mem = mmap(nullptr, ring_total_size(capacity),
               PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    if (mem == MAP_FAILED) { close(fd); delete r; return nullptr; }
    r->shm_fd = fd;
  }
  r->hdr = (RingHeader*)mem;
  r->data = (uint8_t*)mem + sizeof(RingHeader);
  if (create || name == nullptr) {
    r->hdr->head.store(0, std::memory_order_relaxed);
    r->hdr->tail.store(0, std::memory_order_relaxed);
    r->hdr->capacity = capacity;
    // publish magic LAST: an attacher acquiring it is guaranteed to see
    // the initialized header fields
    __atomic_store_n(&r->hdr->magic, RB_MAGIC, __ATOMIC_RELEASE);
  } else if (__atomic_load_n(&r->hdr->magic, __ATOMIC_ACQUIRE) != RB_MAGIC) {
    munmap(mem, ring_total_size(capacity));
    close(r->shm_fd);
    delete r;
    return nullptr;
  }
  return r;
}

void rb_destroy(Ring* r) {
  if (!r) return;
  if (r->shm_fd >= 0) {
    munmap(r->hdr, ring_total_size(r->hdr->capacity));
    close(r->shm_fd);
    if (r->owner && r->name[0]) shm_unlink(r->name);
  } else {
    ::free(r->hdr);
  }
  delete r;
}

uint64_t rb_capacity(Ring* r) { return r->hdr->capacity; }

uint64_t rb_readable(Ring* r) {
  return r->hdr->head.load(std::memory_order_acquire) -
         r->hdr->tail.load(std::memory_order_relaxed);
}

static void copy_in(Ring* r, uint64_t pos, const uint8_t* src, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (off + n <= cap) ? n : cap - off;
  std::memcpy(r->data + off, src, first);
  if (first < n) std::memcpy(r->data, src + first, n - first);
}

static void copy_out(Ring* r, uint64_t pos, uint8_t* dst, uint64_t n) {
  uint64_t cap = r->hdr->capacity;
  uint64_t off = pos % cap;
  uint64_t first = (off + n <= cap) ? n : cap - off;
  std::memcpy(dst, r->data + off, first);
  if (first < n) std::memcpy(dst + first, r->data, n - first);
}

// Frame one batch in; returns 1 on success, 0 if the ring lacks space
// (backpressure — the reference's buffer-pool-exhaustion signal).
int rb_write(Ring* r, const uint8_t* buf, uint32_t len) {
  if (len == 0) return 1;  // empty frames would collide with the
                           // consumer's ring-empty sentinel
  uint64_t need = 4ull + len;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  if (r->hdr->capacity - (head - tail) < need) return 0;
  copy_in(r, head, (const uint8_t*)&len, 4);
  copy_in(r, head + 4, buf, len);
  r->hdr->head.store(head + need, std::memory_order_release);
  return 1;
}

// Drain one framed batch into out (max_len bytes); returns payload size,
// 0 if empty, -1 if out is too small (batch left in place).
int64_t rb_read(Ring* r, uint8_t* out, uint64_t max_len) {
  uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (head == tail) return 0;
  uint32_t len;
  copy_out(r, tail, (uint8_t*)&len, 4);
  if (len > max_len) return -1;
  copy_out(r, tail + 4, out, len);
  r->hdr->tail.store(tail + 4ull + len, std::memory_order_release);
  return (int64_t)len;
}

// ---------------------------------------------------------------- codec
// Encode columns -> wire bytes (producer side).
int64_t records_encode(const uint64_t* keys, const int64_t* ts,
                       const float* vals, uint64_t n, uint8_t* out,
                       uint64_t out_len) {
  const uint64_t need = n * 20ull;
  if (out_len < need) return -1;
  uint8_t* p = out;
  for (uint64_t i = 0; i < n; i++) {
    std::memcpy(p, &keys[i], 8); p += 8;
    std::memcpy(p, &ts[i], 8);  p += 8;
    std::memcpy(p, &vals[i], 4); p += 4;
  }
  return (int64_t)need;
}

// Decode wire bytes -> columns (consumer side, straight into numpy
// buffers). Returns record count, -1 on frame error.
int64_t records_decode(const uint8_t* in, uint64_t in_len, uint64_t* keys,
                       int64_t* ts, float* vals, uint64_t max_n) {
  if (in_len % 20 != 0) return -1;
  uint64_t n = in_len / 20;
  if (n > max_n) return -1;
  const uint8_t* p = in;
  for (uint64_t i = 0; i < n; i++) {
    std::memcpy(&keys[i], p, 8); p += 8;
    std::memcpy(&ts[i], p, 8);  p += 8;
    std::memcpy(&vals[i], p, 4); p += 4;
  }
  return (int64_t)n;
}

}  // extern "C"
