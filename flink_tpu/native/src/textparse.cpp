// Columnar text ingestion: one-pass parse of newline-delimited
// "<int_ts> tok tok ...\n" byte buffers into (ts, token-hash, offset,
// length) arrays — the data-loader hot path of the SocketWindowWordCount
// shape (ref flink-examples SocketWindowWordCount.java:76-79, where a
// per-line Java flatMap does the splitting; here the split/parse/hash
// runs native once per batch and the framework keys on 64-bit token
// identities, materializing strings only for first-seen tokens).
//
// Contract (exported C ABI, bound via ctypes in native/__init__.py):
//   tp_parse(buf, len, ts_out, id_out, off_out, len_out, cap, consumed)
//     -> number of tokens written (>= 0)
//   * only COMPLETE lines are consumed; *consumed reports the byte
//     prefix processed, so a streaming caller keeps the partial tail.
//   * a line whose first field is not a valid integer is skipped whole
//     (robustness against noise on the socket, counted by the caller
//     via consumed bookkeeping if desired).
//   * if the next line's tokens would overflow `cap`, parsing stops
//     BEFORE that line; the caller re-offers the remainder.
//   * token hash: FNV-1a 64 over the token bytes (stable across runs
//     and processes — ids are safe to checkpoint).

#include <cstdint>
#include <cstddef>

extern "C" {

static inline uint64_t fnv1a64(const uint8_t* p, int64_t n) {
    uint64_t h = 1469598103934665603ULL;
    for (int64_t i = 0; i < n; ++i) {
        h ^= (uint64_t)p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

int64_t tp_parse(const uint8_t* buf, int64_t len,
                 int64_t* ts_out, uint64_t* id_out,
                 int64_t* off_out, int32_t* len_out,
                 int64_t cap, int64_t* consumed) {
    int64_t n = 0;        // tokens written
    int64_t pos = 0;      // scan position
    *consumed = 0;
    while (pos < len) {
        // find the end of this line; an incomplete tail stays unconsumed
        int64_t eol = pos;
        while (eol < len && buf[eol] != '\n') ++eol;
        if (eol == len) break;                 // no newline: partial line

        int64_t i = pos;
        while (i < eol && buf[i] == ' ') ++i;  // leading spaces
        // parse the leading integer timestamp
        bool neg = false;
        if (i < eol && (buf[i] == '-' || buf[i] == '+')) {
            neg = buf[i] == '-';
            ++i;
        }
        int64_t ts = 0;
        bool any_digit = false;
        while (i < eol && buf[i] >= '0' && buf[i] <= '9') {
            ts = ts * 10 + (buf[i] - '0');
            any_digit = true;
            ++i;
        }
        bool ok = any_digit && (i == eol || buf[i] == ' ');
        if (!ok) {                             // malformed: skip the line
            pos = eol + 1;
            *consumed = pos;
            continue;
        }
        if (neg) ts = -ts;

        // count this line's tokens first: the line is all-or-nothing
        // against cap so a caller never sees a line split across calls
        int64_t count = 0;
        int64_t j = i;
        while (j < eol) {
            while (j < eol && buf[j] == ' ') ++j;
            if (j == eol) break;
            ++count;
            while (j < eol && buf[j] != ' ') ++j;
        }
        if (n + count > cap) break;            // re-offer from this line

        j = i;
        while (j < eol) {
            while (j < eol && buf[j] == ' ') ++j;
            if (j == eol) break;
            int64_t tok = j;
            while (j < eol && buf[j] != ' ') ++j;
            ts_out[n] = ts;
            id_out[n] = fnv1a64(buf + tok, j - tok);
            off_out[n] = tok;
            len_out[n] = (int32_t)(j - tok);
            ++n;
        }
        pos = eol + 1;
        *consumed = pos;
    }
    return n;
}

}  // extern "C"
