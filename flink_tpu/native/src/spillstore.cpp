// Host spill store — the RocksDB-replacement seam (SURVEY §2.10 item 2).
//
// The reference keeps cold keyed state in embedded RocksDB (C++ via JNI)
// when it exceeds the JVM heap. Here the primary store is device HBM
// (hash-slot arrays); this C++ store is the host-side overflow tier the
// backend evicts cold (key -> accumulator block) entries into, batch-first:
// put/get/delete take whole arrays per call so the Python boundary is
// crossed once per micro-batch, not per key (the JNI-per-access cost the
// reference pays is the lesson).
//
// Layout: open-addressing hash table (u64 key -> fixed-width float block),
// linear probing, power-of-two capacity, automatic grow at 70% load.
// Persistence: save/load to a flat file (checkpoint integration).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {

struct Spill {
  std::vector<uint64_t> keys;   // 0 = empty (key 0 remapped)
  std::vector<uint8_t> used;
  std::vector<float> vals;      // capacity * width
  uint64_t capacity;
  uint64_t width;               // floats per value block
  uint64_t count;
};

static uint64_t mix(uint64_t k) {
  k ^= k >> 33; k *= 0xff51afd7ed558ccdull;
  k ^= k >> 33; k *= 0xc4ceb9fe1a85ec53ull;
  k ^= k >> 33; return k;
}

Spill* spill_create(uint64_t initial_capacity, uint64_t width) {
  uint64_t cap = 16;
  while (cap < initial_capacity) cap <<= 1;
  Spill* s = new Spill();
  s->capacity = cap;
  s->width = width;
  s->count = 0;
  s->keys.assign(cap, 0);
  s->used.assign(cap, 0);
  s->vals.assign(cap * width, 0.f);
  return s;
}

void spill_destroy(Spill* s) { delete s; }
uint64_t spill_count(Spill* s) { return s->count; }
uint64_t spill_capacity(Spill* s) { return s->capacity; }
uint64_t spill_width(Spill* s) { return s->width; }

static uint64_t find_slot(Spill* s, uint64_t key, int* found) {
  uint64_t mask = s->capacity - 1;
  uint64_t i = mix(key) & mask;
  while (s->used[i]) {
    if (s->keys[i] == key) { *found = 1; return i; }
    i = (i + 1) & mask;
  }
  *found = 0;
  return i;
}

static void grow(Spill* s) {
  Spill* bigger = spill_create(s->capacity * 2, s->width);
  for (uint64_t i = 0; i < s->capacity; i++) {
    if (!s->used[i]) continue;
    int f;
    uint64_t j = find_slot(bigger, s->keys[i], &f);
    bigger->used[j] = 1;
    bigger->keys[j] = s->keys[i];
    std::memcpy(&bigger->vals[j * s->width], &s->vals[i * s->width],
                s->width * sizeof(float));
    bigger->count++;
  }
  s->keys.swap(bigger->keys);
  s->used.swap(bigger->used);
  s->vals.swap(bigger->vals);
  s->capacity = bigger->capacity;
  delete bigger;
}

// Batch upsert: n entries, values is [n * width].
void spill_put_batch(Spill* s, const uint64_t* keys, const float* values,
                     uint64_t n) {
  for (uint64_t k = 0; k < n; k++) {
    if ((s->count + 1) * 10 > s->capacity * 7) grow(s);
    int f;
    uint64_t i = find_slot(s, keys[k], &f);
    if (!f) { s->used[i] = 1; s->keys[i] = keys[k]; s->count++; }
    std::memcpy(&s->vals[i * s->width], &values[k * s->width],
                s->width * sizeof(float));
  }
}

// Batch get: fills values [n * width] and found [n]; missing -> zeros.
void spill_get_batch(Spill* s, const uint64_t* keys, float* values,
                     uint8_t* found, uint64_t n) {
  for (uint64_t k = 0; k < n; k++) {
    int f;
    uint64_t i = find_slot(s, keys[k], &f);
    found[k] = (uint8_t)f;
    if (f) {
      std::memcpy(&values[k * s->width], &s->vals[i * s->width],
                  s->width * sizeof(float));
    } else {
      std::memset(&values[k * s->width], 0, s->width * sizeof(float));
    }
  }
}

// Batch delete (eviction promoted back to the device); returns #removed.
uint64_t spill_delete_batch(Spill* s, const uint64_t* keys, uint64_t n) {
  uint64_t removed = 0;
  uint64_t mask = s->capacity - 1;
  for (uint64_t k = 0; k < n; k++) {
    int f;
    uint64_t i = find_slot(s, keys[k], &f);
    if (!f) continue;
    // backward-shift deletion keeps probe chains intact
    s->used[i] = 0;
    s->count--;
    removed++;
    uint64_t j = i;
    while (true) {
      j = (j + 1) & mask;
      if (!s->used[j]) break;
      uint64_t home = mix(s->keys[j]) & mask;
      // can slot j's entry legally move into the hole at i?
      uint64_t dist_cur = (j - home) & mask;
      uint64_t dist_new = (i - home) & mask;
      if (dist_new <= dist_cur) {
        s->keys[i] = s->keys[j];
        std::memcpy(&s->vals[i * s->width], &s->vals[j * s->width],
                    s->width * sizeof(float));
        s->used[i] = 1;
        s->used[j] = 0;
        i = j;
      }
    }
  }
  return removed;
}

// Dump all live entries (for snapshots): keys_out [count], vals_out
// [count * width]; returns count written (caller sizes via spill_count).
uint64_t spill_dump(Spill* s, uint64_t* keys_out, float* vals_out,
                    uint64_t max_n) {
  uint64_t w = 0;
  for (uint64_t i = 0; i < s->capacity && w < max_n; i++) {
    if (!s->used[i]) continue;
    keys_out[w] = s->keys[i];
    std::memcpy(&vals_out[w * s->width], &s->vals[i * s->width],
                s->width * sizeof(float));
    w++;
  }
  return w;
}

int spill_save(Spill* s, const char* path) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return 0;
  uint64_t hdr[3] = {s->count, s->width, 0x53504c4cull};
  std::fwrite(hdr, sizeof(uint64_t), 3, f);
  for (uint64_t i = 0; i < s->capacity; i++) {
    if (!s->used[i]) continue;
    std::fwrite(&s->keys[i], sizeof(uint64_t), 1, f);
    std::fwrite(&s->vals[i * s->width], sizeof(float), s->width, f);
  }
  std::fclose(f);
  return 1;
}

Spill* spill_load(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  uint64_t hdr[3];
  if (std::fread(hdr, sizeof(uint64_t), 3, f) != 3 || hdr[2] != 0x53504c4cull) {
    std::fclose(f);
    return nullptr;
  }
  Spill* s = spill_create(hdr[0] * 2 + 16, hdr[1]);
  std::vector<float> block(hdr[1]);
  for (uint64_t k = 0; k < hdr[0]; k++) {
    uint64_t key;
    if (std::fread(&key, sizeof(uint64_t), 1, f) != 1 ||
        std::fread(block.data(), sizeof(float), hdr[1], f) != hdr[1]) {
      std::fclose(f);
      spill_destroy(s);
      return nullptr;
    }
    spill_put_batch(s, &key, block.data(), 1);
  }
  std::fclose(f);
  return s;
}

}  // extern "C"
