"""Non-keyed operator state — the OperatorStateStore analog (SURVEY §2.4,
ref api/common/state/OperatorStateStore + DefaultOperatorStateBackend):
per-OPERATOR (not per-key) list state that snapshots into checkpoints and
restores on recovery. The reference's redistribution modes collapse in
the single-host plan: SPLIT_DISTRIBUTE and UNION both restore the full
list to the one operator instance (documented divergence — with one
subtask they are the same thing).

User functions reach it through RuntimeContext.get_operator_list_state;
objects stay LIVE across checkpoint/restore (contents are swapped in
place, the same contract as accumulators)."""

from __future__ import annotations

import copy
from typing import Any, Dict, List


class OperatorListState:
    """ref ListState under the operator (non-keyed) backend."""

    def __init__(self):
        self._items: List[Any] = []

    def get(self) -> List[Any]:
        return list(self._items)

    def add(self, value):
        self._items.append(value)

    def update(self, values):
        self._items = list(values)

    def clear(self):
        self._items.clear()

    def __len__(self):
        return len(self._items)


class OperatorStateStore:
    """Named operator states of one operator instance."""

    def __init__(self):
        self._states: Dict[str, OperatorListState] = {}

    def get_list_state(self, name: str) -> OperatorListState:
        return self._states.setdefault(name, OperatorListState())

    # union-state alias: identical under a single subtask (see module doc)
    get_union_list_state = get_list_state

    def snapshot(self) -> Dict[str, List[Any]]:
        return {n: copy.deepcopy(s._items) for n, s in self._states.items()}

    def restore(self, snap: Dict[str, List[Any]]):
        """In place: user functions hold live references to their state
        objects, so contents are replaced rather than the objects."""
        for n, items in snap.items():
            self.get_list_state(n)._items = list(items)
        for n, s in self._states.items():
            if n not in snap:
                s._items.clear()


def repartition_round_robin(snapshots: List[Dict[str, List[Any]]],
                            new_parallelism: int
                            ) -> List[Dict[str, List[Any]]]:
    """SPLIT_DISTRIBUTE redistribution across a parallelism change (ref
    RoundRobinOperatorStateRepartitioner.java): all subtasks' items of
    each named state are collected in subtask order, then dealt
    round-robin to the new subtasks — every item lands exactly once, and
    adjacent items spread across instances (the reference's fair
    re-split; exact per-slot placement is unspecified there too, only
    the partition property matters).

    snapshots: per-OLD-subtask OperatorStateStore.snapshot() dicts.
    Returns per-NEW-subtask snapshot dicts (restore() each)."""
    if new_parallelism < 1:
        raise ValueError("new_parallelism must be >= 1")
    names = []
    for snap in snapshots:
        for n in snap:
            if n not in names:
                names.append(n)
    out: List[Dict[str, List[Any]]] = [
        {n: [] for n in names} for _ in range(new_parallelism)
    ]
    for n in names:
        merged = [it for snap in snapshots for it in snap.get(n, [])]
        for i, item in enumerate(merged):
            out[i % new_parallelism][n].append(item)
    return out


def repartition_union(snapshots: List[Dict[str, List[Any]]],
                      new_parallelism: int
                      ) -> List[Dict[str, List[Any]]]:
    """UNION redistribution (ref union state in
    RoundRobinOperatorStateRepartitioner.repartitionUnionState): every
    new subtask receives ALL items of every named state (each instance
    rebuilds its view from the full set — the Kafka-partition-offsets
    pattern)."""
    if new_parallelism < 1:
        raise ValueError("new_parallelism must be >= 1")
    names = []
    for snap in snapshots:
        for n in snap:
            if n not in names:
                names.append(n)
    full = {
        n: [it for snap in snapshots for it in snap.get(n, [])]
        for n in names
    }
    return [copy.deepcopy(full) for _ in range(new_parallelism)]
