from flink_tpu.state.descriptors import (  # noqa: F401
    AggregatingStateDescriptor,
    ListStateDescriptor,
    ReducingStateDescriptor,
    StateDescriptor,
    ValueStateDescriptor,
)
