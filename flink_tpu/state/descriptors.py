"""Keyed-state descriptors — the user-facing state API.

Mirrors the contracts of the reference's state API (SURVEY §2.1:
State.java:32, ValueState.java:40, ReducingState.java:38, FoldingState.java:40,
StateDescriptor.java:50): a descriptor names a state, fixes its type, and (for
reducing/aggregating kinds) carries the combine function. TPU-adapted: types
are dtypes + trailing shapes (device columns), and combine functions must be
jnp-traceable & associative so a whole key-group shard can be updated as one
kernel. FoldingState (deprecated in the reference line) is subsumed by
AggregatingState here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Tuple

import jax.numpy as jnp

from flink_tpu.ops.window_kernels import ReduceSpec


@dataclass(frozen=True)
class StateDescriptor:
    name: str
    dtype: Any = jnp.float32
    value_shape: Tuple[int, ...] = ()
    # optional per-state TypeSerializer (core/serializers.py) pinning how
    # this state's values are written into snapshots — the descriptor-level
    # serializer injection of the reference (StateDescriptor.java:50).
    # None = the job's SerializerRegistry picks by value type.
    serializer: Any = None

    def to_reduce_spec(self) -> ReduceSpec:
        raise NotImplementedError


@dataclass(frozen=True)
class ValueStateDescriptor(StateDescriptor):
    """Single value per key; update semantics = last write wins."""

    default: Any = None

    def to_reduce_spec(self) -> ReduceSpec:
        # last-write-wins is associative: combine(a, b) = b
        return ReduceSpec(
            "generic", self.dtype, self.value_shape,
            combine=lambda a, b: b,
            neutral=self.default if self.default is not None else 0,
        )


@dataclass(frozen=True)
class ReducingStateDescriptor(StateDescriptor):
    """add(v) folds v into the accumulator with an associative reduce."""

    kind: str = "sum"  # 'sum' | 'min' | 'max' | 'count' | 'generic'
    reduce_fn: Optional[Callable] = None
    neutral: Any = None

    def to_reduce_spec(self) -> ReduceSpec:
        return ReduceSpec(
            self.kind, self.dtype, self.value_shape,
            combine=self.reduce_fn, neutral=self.neutral,
        )

    def host_reduce(self, a, b):
        """Scalar combine for the heap backend (same semantics the device
        kernel applies shard-wide)."""
        if self.kind == "sum" or self.kind == "count":
            return a + b
        if self.kind == "min":
            return min(a, b)
        if self.kind == "max":
            return max(a, b)
        return self.reduce_fn(a, b)


@dataclass(frozen=True)
class AggregatingStateDescriptor(StateDescriptor):
    """Accumulator-style aggregation (ref AggregateFunction contract):

    add:       (acc, value) -> acc     — fold one input into the accumulator
    merge:     (acc, acc) -> acc       — associative accumulator merge
    get_result:(acc) -> out            — host- or device-side projection

    The accumulator (not the input) is what lives per (key, pane) on device;
    value_shape/dtype describe the ACCUMULATOR columns.
    """

    add: Optional[Callable] = None
    merge: Optional[Callable] = None
    get_result: Optional[Callable] = None
    acc_init: Any = 0

    def to_reduce_spec(self) -> ReduceSpec:
        return ReduceSpec(
            "generic", self.dtype, self.value_shape,
            combine=self.merge, neutral=self.acc_init,
        )

    def create_accumulator(self):
        init = self.acc_init
        return init() if callable(init) else init


@dataclass(frozen=True)
class FoldingStateDescriptor(AggregatingStateDescriptor):
    """FoldingStateDescriptor.java:37 parity: fold(acc, value) -> acc.
    Deprecated in the reference line; an AggregatingState whose `add` is the
    fold function."""

    fold_fn: Optional[Callable] = None

    def __post_init__(self):
        if self.fold_fn is not None and self.add is None:
            object.__setattr__(self, "add", self.fold_fn)


@dataclass(frozen=True)
class MapStateDescriptor(StateDescriptor):
    """Per-key {user_key: value} map (heap backend only; device state is
    fixed-width columns)."""


@dataclass(frozen=True)
class ListStateDescriptor(StateDescriptor):
    """Bounded per-key element buffer (device lists are fixed-capacity rings).

    max_elements bounds the on-device buffer, the analog of evictor-bounded
    ListState in the reference's EvictingWindowOperator.
    """

    max_elements: int = 16
