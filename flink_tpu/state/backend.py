"""Keyed state backends — the AbstractStateBackend seam.

Reproduces the contracts of the reference's state-backend SPI (SURVEY §2.4:
AbstractStateBackend.java:32 createKeyedStateBackend:51,
AbstractKeyedStateBackend.java:52 with setCurrentKey:167 /
getPartitionedState:216, and the heap backend HeapKeyedStateBackend.java:65
with its StateTable.java:36 nested per-key-group maps), TPU-adapted:

* The **device** backend is the sharded array state driven by the compiled
  SPMD steps (ops/window_kernels, ops/rolling, ...) — that is the hot path
  and lives in runtime/step.py.
* The **heap** backend here is the host-side general-purpose backend backing
  arbitrary user logic (ProcessFunction / custom triggers / evictors / CEP
  bookkeeping), exactly the role the reference's HeapKeyedStateBackend plays
  for the RocksDB-less deployments: per (state-name, key-group, namespace,
  key) values in Python dicts, snapshotted per key group so restore and
  rescale re-slice key-group ranges (KeyGroupRangeAssignment semantics,
  core/keygroups.py).
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.keygroups import (
    DEFAULT_MAX_PARALLELISM,
    KeyGroupRange,
    assign_to_key_group,
    key_group_range_for_operator,
)
from flink_tpu.core.serializers import SerializationError
from flink_tpu.ops.hashing import hash64_host
from flink_tpu.state.descriptors import (
    AggregatingStateDescriptor,
    ListStateDescriptor,
    MapStateDescriptor,
    ReducingStateDescriptor,
    StateDescriptor,
    ValueStateDescriptor,
)

VoidNamespace = ()  # the reference's VoidNamespace singleton


def key_group_of(key, max_parallelism: int) -> int:
    """Host key -> key group (KeyGroupRangeAssignment.assignToKeyGroup)."""
    h64 = int(hash64_host([key])[0])
    return int(assign_to_key_group(np.uint32(h64 & 0xFFFFFFFF), max_parallelism))


class StateTable:
    """name -> per-key-group dict of {namespace: {key: value}}.

    Mirrors the reference's StateTable.java:36 layout (one map per key group)
    so snapshots are naturally partitioned by key group.
    """

    def __init__(self, key_group_range: KeyGroupRange, max_parallelism: int):
        self.kgr = key_group_range
        self.max_parallelism = max_parallelism
        # maps[key_group - start] : {namespace: {key: value}}
        self.maps: List[Dict[Any, Dict[Any, Any]]] = [
            {} for _ in range(key_group_range.num_key_groups)
        ]

    def _map_for(self, key_group: int) -> Dict[Any, Dict[Any, Any]]:
        idx = key_group - self.kgr.start
        if idx < 0 or idx >= len(self.maps):
            raise KeyError(
                f"key group {key_group} outside owned range "
                f"[{self.kgr.start}, {self.kgr.end}]"
            )
        return self.maps[idx]

    def get(self, key_group, namespace, key, default=None):
        return self._map_for(key_group).get(namespace, {}).get(key, default)

    def put(self, key_group, namespace, key, value):
        self._map_for(key_group).setdefault(namespace, {})[key] = value

    def remove(self, key_group, namespace, key):
        ns = self._map_for(key_group).get(namespace)
        if ns is not None:
            ns.pop(key, None)
            if not ns:
                self._map_for(key_group).pop(namespace, None)

    def namespaces(self, key_group):
        return list(self._map_for(key_group).keys())

    def entries(self):
        """Iterate (key_group, namespace, key, value)."""
        for i, m in enumerate(self.maps):
            kg = self.kgr.start + i
            for ns, kv in m.items():
                for k, v in kv.items():
                    yield kg, ns, k, v

    def size(self) -> int:
        return sum(len(kv) for m in self.maps for kv in m.values())


# --------------------------------------------------------------------------
# State objects (the State.java:32 hierarchy)
# --------------------------------------------------------------------------


class State:
    """Base: a view over one (descriptor, current key, namespace) cell."""

    def __init__(self, backend: "HeapKeyedStateBackend", desc: StateDescriptor):
        self._b = backend
        self._d = desc
        self._table = backend._table_for(desc)
        self._namespace = VoidNamespace

    def set_namespace(self, ns):
        self._namespace = ns

    def _cell(self, default=None):
        # reads mark too: accessors hand back LIVE containers
        # (ListState.get() returns the stored list) that callers may
        # mutate without ever calling update()/add(), and the snapshot
        # blob cache must never serve bytes that predate such a
        # mutation. Over-marking read-only access only costs the cache
        # on groups that were touched at all — untouched groups (the
        # point of the cache) still skip re-serialization.
        self._mark()
        return self._table.get(
            self._b.current_key_group, self._namespace, self._b.current_key,
            default,
        )

    def _mark(self):
        # changelog seam (flink_tpu/checkpointing): every touch marks
        # the key group dirty so an incremental snapshot re-serializes
        # only changed groups. Mutators that bypass both _put and _cell
        # must call this directly.
        self._b.changelog.mark(self._b.current_key_group)

    def _put(self, value):
        self._mark()
        self._table.put(
            self._b.current_key_group, self._namespace, self._b.current_key,
            value,
        )

    def clear(self):
        self._mark()
        self._table.remove(
            self._b.current_key_group, self._namespace, self._b.current_key
        )


class ValueState(State):
    """ValueState.java:40 — value()/update()."""

    def value(self):
        v = self._cell()
        if v is None:
            return self._d.default
        return v

    def update(self, v):
        self._put(v)


class ListState(State):
    """ListState.java — get()/add(); AppendingState contract."""

    def get(self):
        return self._cell(default=[])

    def add(self, v):
        cur = self._cell()      # marks dirty (in-place append below)
        if cur is None:
            cur = []
            self._put(cur)
        cur.append(v)

    def update(self, values):
        self._put(list(values))


class ReducingState(State):
    """ReducingState.java:38 — add() folds with the descriptor's reduce fn."""

    def get(self):
        return self._cell()

    def add(self, v):
        if self._d.kind == "count":
            # count semantics match the device kernel: +1 per add,
            # regardless of the value (window_kernels 'count' branch)
            cur = self._cell()
            self._put(1 if cur is None else cur + 1)
            return
        cur = self._cell()
        if cur is None:
            self._put(v)
        else:
            self._put(self._d.host_reduce(cur, v))


class AggregatingState(State):
    """AggregateFunction-backed accumulator state (subsumes FoldingState)."""

    def get(self):
        acc = self._cell()
        if acc is None:
            return None
        return self._d.get_result(acc) if self._d.get_result else acc

    def add(self, v):
        acc = self._cell()
        if acc is None:
            acc = self._d.create_accumulator()
        self._put(self._d.add(acc, v))

    def get_accumulator(self):
        return self._cell()

    def merge_accumulator(self, other_acc, merge_fn=None):
        """Fold another accumulator in (window-merge path; the input-`add`
        path cannot express acc x acc)."""
        merge_fn = merge_fn or self._d.merge
        cur = self._cell()
        self._put(other_acc if cur is None else merge_fn(cur, other_acc))


class FoldingState(AggregatingState):
    """FoldingState.java:40 — fold(acc, value); kept for reference parity,
    deprecated in the reference line in favor of aggregating state."""


class MapState(State):
    """Per-key map state (host backend extension; the reference adds
    MapState in 1.3 — included because user ProcessFunctions commonly
    need it and it costs nothing on the heap backend)."""

    def get(self, user_key, default=None):
        m = self._cell()
        return default if m is None else m.get(user_key, default)

    def put(self, user_key, v):
        m = self._cell()        # marks dirty (in-place mutation below)
        if m is None:
            m = {}
            self._put(m)
        m[user_key] = v

    def remove(self, user_key):
        m = self._cell()        # marks dirty
        if m:
            m.pop(user_key, None)

    def contains(self, user_key):
        m = self._cell()
        return bool(m) and user_key in m

    def items(self):
        m = self._cell()
        return [] if m is None else list(m.items())

    def keys(self):
        m = self._cell()
        return [] if m is None else list(m.keys())

    def values(self):
        m = self._cell()
        return [] if m is None else list(m.values())

    def is_empty(self):
        return not self._cell()


_STATE_CLASS = {
    ValueStateDescriptor: ValueState,
    ListStateDescriptor: ListState,
    ReducingStateDescriptor: ReducingState,
    AggregatingStateDescriptor: AggregatingState,
    MapStateDescriptor: MapState,
}


# --------------------------------------------------------------------------
# Backends
# --------------------------------------------------------------------------


class KeyedStateBackend:
    """AbstractKeyedStateBackend contract (ref :52): current-key context +
    per-descriptor state handles + key-grouped snapshot/restore."""

    def set_current_key(self, key):
        raise NotImplementedError

    def get_partitioned_state(self, descriptor, namespace=VoidNamespace):
        raise NotImplementedError

    def snapshot(self) -> Dict[int, bytes]:
        """-> {key_group: serialized state}. The key-grouped layout is what
        makes restore-with-different-parallelism a pure re-slice
        (StateAssignmentOperation semantics)."""
        raise NotImplementedError

    def restore(self, key_group_blobs: Dict[int, bytes]) -> None:
        raise NotImplementedError


class HeapKeyedStateBackend(KeyedStateBackend):
    def __init__(self, key_group_range: Optional[KeyGroupRange] = None,
                 max_parallelism: int = DEFAULT_MAX_PARALLELISM):
        self.kgr = key_group_range or KeyGroupRange(0, max_parallelism - 1)
        self.max_parallelism = max_parallelism
        self._tables: Dict[str, StateTable] = {}
        self._descs: Dict[str, StateDescriptor] = {}
        # changelog + per-key-group blob cache (flink_tpu/checkpointing):
        # snapshot() re-serializes only the key groups the State views
        # marked dirty since the last snapshot and reuses the cached
        # bytes for clean ones — a 1M-key backend with 100 hot keys per
        # interval re-pickles 100 keys' groups, not 1M
        from flink_tpu.checkpointing.changelog import HostChangelog

        self.changelog = HostChangelog()
        self._blob_cache: Dict[int, Optional[bytes]] = {}
        self.current_key = None
        self.current_key_group = None
        # job-scoped SerializerRegistry; None -> process default
        self.serializer_registry = None
        # state-name -> [(kg, uid, cfg, ns_b, k_b, v_b)] entries whose
        # pinned serializer was unknown at restore time; decoded when the
        # descriptor shows up (lazily-registered state)
        self._pending_restore: Dict[str, list] = {}

    # -- key context ----------------------------------------------------
    def set_current_key(self, key):
        self.current_key = key
        self.current_key_group = key_group_of(key, self.max_parallelism)

    # -- state handles ---------------------------------------------------
    def _table_for(self, desc: StateDescriptor) -> StateTable:
        t = self._tables.get(desc.name)
        if t is None:
            t = StateTable(self.kgr, self.max_parallelism)
            self._tables[desc.name] = t
        # record the descriptor even when restore() pre-created the table:
        # snapshot() resolves the pinned serializer through _descs, and a
        # descriptor first seen after restore must still pin
        self._descs[desc.name] = desc
        self._resolve_pending_restore(desc)
        return t

    def _resolve_pending_restore(self, desc: StateDescriptor):
        """Decode entries restored before this state's pinned serializer
        was known (lazily-registered descriptor with serializer=...)."""
        pend = self._pending_restore.pop(desc.name, None)
        if not pend:
            return
        from flink_tpu.core.serializers import SerializationError

        reg = self._registry()
        ser = getattr(desc, "serializer", None)
        table = self._tables[desc.name]
        for kg, uid, cfg, ns_b, k_b, v_b in pend:
            if ser is None or ser.uid != uid:
                raise SerializationError(
                    f"state {desc.name!r} was snapshotted with pinned "
                    f"serializer {uid!r} but the descriptor now pins "
                    f"{getattr(ser, 'uid', None)!r}"
                )
            if cfg and ser.config_snapshot() != cfg:
                raise SerializationError(
                    f"state {desc.name!r}: serializer {uid!r} config "
                    f"changed since snapshot ({cfg!r} -> "
                    f"{ser.config_snapshot()!r}); restore refused"
                )
            m = table.maps[kg - self.kgr.start]
            ns = reg.loads_typed(ns_b)
            k = reg.loads_typed(k_b)
            m.setdefault(ns, {})[k] = ser.deserialize(v_b)
            self.changelog.mark(kg)     # bypasses the State-view seam

    def get_partitioned_state(self, descriptor, namespace=VoidNamespace):
        # Returns a FRESH view object per call: callers may hold several
        # handles to the same state under different namespaces at once
        # (e.g. session-merge moving contents between windows), so views
        # must not alias. The underlying table is shared by name.
        cls = _STATE_CLASS.get(type(descriptor))
        if cls is None:
            for base, c in _STATE_CLASS.items():
                if isinstance(descriptor, base):
                    cls = c
                    break
        if cls is None:
            raise TypeError(f"unsupported descriptor {type(descriptor)}")
        st = cls(self, descriptor)
        st.set_namespace(namespace)
        return st

    # -- introspection (queryable state read path) -----------------------
    def lookup(self, state_name: str, key, namespace=VoidNamespace):
        """Point lookup without disturbing the current-key context
        (KvState.getSerializedValue role)."""
        t = self._tables.get(state_name)
        if t is None:
            return None
        kg = key_group_of(key, self.max_parallelism)
        return t.get(kg, namespace, key)

    def keys(self, state_name: str, namespace=VoidNamespace):
        t = self._tables.get(state_name)
        if t is None:
            return []
        return [k for kg, ns, k, _ in t.entries() if ns == namespace]

    # -- snapshot / restore ----------------------------------------------
    # Per-key-group wire format "FTS2" (replaces round-1 blanket pickle;
    # TypeSerializer seam, ref TypeSerializer.java:39):
    #   magic | n_states | per state:
    #     name | pinned-serializer uid ('' = registry-typed) | n_entries |
    #     per entry: ns (typed envelope) | key (typed) | value (pinned
    #     serializer bytes, or typed envelope)
    # All strings/blobs are u32-length-framed. Custom value types snapshot
    # through serializers registered on the registry (or pinned on the
    # descriptor) and demand the same registration on restore.
    _SNAP_MAGIC = b"FTS2"

    @staticmethod
    def _frame(out: list, blob: bytes):
        import struct as _st

        out.append(_st.pack("<I", len(blob)))
        out.append(blob)

    @staticmethod
    def _unframe(data: bytes, off: int):
        import struct as _st

        (ln,) = _st.unpack_from("<I", data, off)
        off += 4
        return data[off:off + ln], off + ln

    def _registry(self):
        from flink_tpu.core.serializers import DEFAULT_REGISTRY

        return getattr(self, "serializer_registry", None) or DEFAULT_REGISTRY

    def snapshot(self) -> Dict[int, bytes]:
        import struct as _st

        reg = self._registry()
        out: Dict[int, bytes] = {}
        # still-deferred restore entries (state restored but its pinned
        # descriptor never opened since) must survive into the next
        # snapshot verbatim, or an untouched state silently vanishes
        pending_by_kg: Dict[int, list] = {}
        for name, pend in self._pending_restore.items():
            for kg, uid, cfg, ns_b, k_b, v_b in pend:
                pending_by_kg.setdefault(kg, []).append(
                    (name, uid, cfg, ns_b, k_b, v_b)
                )
        # changelog: key groups untouched since the last snapshot reuse
        # their cached serialization (None = group was empty)
        dirty = self.changelog.consume()
        for kg in self.kgr:
            if kg not in dirty and kg in self._blob_cache:
                blob = self._blob_cache[kg]
                if blob is not None:
                    out[kg] = blob
                continue
            self._snapshot_one(kg, pending_by_kg, reg, out, _st)
            self._blob_cache[kg] = out.get(kg)
        return out

    def _snapshot_one(self, kg, pending_by_kg, reg, out, _st):
        """Serialize ONE key group into out[kg] (absent = empty group)."""
        states = []
        for name, uid, cfg, ns_b, k_b, v_b in pending_by_kg.get(kg, ()):
            buf: list = []
            self._frame(buf, name.encode("utf-8"))
            self._frame(buf, uid.encode("ascii"))
            self._frame(buf, cfg.encode("utf-8"))
            buf.append(_st.pack("<I", 1))
            self._frame(buf, ns_b)
            self._frame(buf, k_b)
            self._frame(buf, v_b)
            states.append(b"".join(buf))
        for name, table in self._tables.items():
            m = table._map_for(kg)
            if not m:
                continue
            desc = self._descs.get(name)
            pinned = getattr(desc, "serializer", None)
            buf: list = []
            self._frame(buf, name.encode("utf-8"))
            self._frame(buf, (pinned.uid if pinned else "").encode("ascii"))
            # restore-compatibility token (TypeSerializerConfigSnapshot
            # role): restore refuses a same-uid serializer whose config
            # snapshot differs instead of misreading bytes
            self._frame(
                buf,
                (pinned.config_snapshot() if pinned else "").encode("utf-8"),
            )
            entries = [
                (ns, k, v) for ns, kv in m.items() for k, v in kv.items()
            ]
            buf.append(_st.pack("<I", len(entries)))
            for ns, k, v in entries:
                self._frame(buf, reg.dumps_typed(ns))
                self._frame(buf, reg.dumps_typed(k))
                self._frame(
                    buf, pinned.serialize(v) if pinned
                    else reg.dumps_typed(v)
                )
            states.append(b"".join(buf))
        if states:
            out[kg] = (
                self._SNAP_MAGIC + _st.pack("<I", len(states))
                + b"".join(states)
            )

    def restore(self, key_group_blobs: Dict[int, bytes]) -> None:
        import struct as _st

        # Restore replaces ALL owned state: key groups absent from the
        # snapshot were empty at checkpoint time and must be empty after
        # restore, or replayed records double-apply (exactly-once contract).
        reg = self._registry()
        # the changelog/cache describe the REPLACED state: drop both (the
        # restored blobs could seed the cache, but a restore may re-slice
        # foreign-parallelism blobs, so correctness over cleverness)
        self._blob_cache.clear()
        self.changelog = type(self.changelog)()
        for table in self._tables.values():
            table.maps = [{} for _ in range(self.kgr.num_key_groups)]
        # deferred entries from any PREVIOUS restore are part of the state
        # being replaced — never resurrect them after this restore
        self._pending_restore.clear()
        for kg, blob in key_group_blobs.items():
            if kg < self.kgr.start or kg > self.kgr.end:
                continue
            if blob[:4] != self._SNAP_MAGIC:
                # round-1 format: whole key group pickled
                per_kg = pickle.loads(blob)
                for name, m in per_kg.items():
                    if name not in self._tables:
                        self._tables[name] = StateTable(
                            self.kgr, self.max_parallelism
                        )
                    self._tables[name].maps[kg - self.kgr.start] = m
                continue
            (n_states,) = _st.unpack_from("<I", blob, 4)
            off = 8
            for _ in range(n_states):
                nm, off = self._unframe(blob, off)
                name = nm.decode("utf-8")
                uid_b, off = self._unframe(blob, off)
                uid = uid_b.decode("ascii")
                cfg_b, off = self._unframe(blob, off)
                cfg = cfg_b.decode("utf-8")
                pinned = None
                defer = False
                if uid:
                    # a descriptor-pinned serializer need not be in the
                    # registry: the descriptor registered during open()
                    # carries it — resolve there first; if neither knows
                    # the uid yet (state registered lazily on first
                    # record), DEFER decoding until _table_for sees the
                    # descriptor instead of failing the restore
                    desc = self._descs.get(name)
                    desc_ser = getattr(desc, "serializer", None)
                    if desc_ser is not None and desc_ser.uid == uid:
                        pinned = desc_ser
                    else:
                        try:
                            pinned = reg.by_uid(uid)
                        except SerializationError:
                            defer = True
                    if pinned is not None and cfg and (
                        pinned.config_snapshot() != cfg
                    ):
                        raise SerializationError(
                            f"state {name!r}: serializer {uid!r} config "
                            f"changed since snapshot ({cfg!r} -> "
                            f"{pinned.config_snapshot()!r}); restore refused"
                        )
                (n_entries,) = _st.unpack_from("<I", blob, off)
                off += 4
                if name not in self._tables:
                    # table re-registered lazily on first access; stash now
                    self._tables[name] = StateTable(
                        self.kgr, self.max_parallelism
                    )
                m = self._tables[name].maps[kg - self.kgr.start]
                for _ in range(n_entries):
                    ns_b, off = self._unframe(blob, off)
                    k_b, off = self._unframe(blob, off)
                    v_b, off = self._unframe(blob, off)
                    if defer:
                        self._pending_restore.setdefault(name, []).append(
                            (kg, uid, cfg, ns_b, k_b, v_b)
                        )
                        continue
                    ns = reg.loads_typed(ns_b)
                    k = reg.loads_typed(k_b)
                    v = (
                        pinned.deserialize(v_b) if pinned
                        else reg.loads_typed(v_b)
                    )
                    m.setdefault(ns, {})[k] = v


def rescale_key_group_blobs(
    blobs_per_subtask: List[Dict[int, bytes]],
    new_parallelism: int,
    max_parallelism: int,
) -> List[Dict[int, bytes]]:
    """Re-slice key-grouped snapshots to a new parallelism
    (StateAssignmentOperation.java role): pure dictionary routing, no
    re-hashing of keys."""
    merged: Dict[int, bytes] = {}
    for b in blobs_per_subtask:
        merged.update(b)
    out = []
    for idx in range(new_parallelism):
        r = key_group_range_for_operator(max_parallelism, new_parallelism, idx)
        out.append({kg: blob for kg, blob in merged.items()
                    if r.start <= kg <= r.end})
    return out
