from flink_tpu.core.keygroups import (  # noqa: F401
    KeyGroupRange,
    assign_to_key_group,
    compute_key_group_for_key_hash,
    compute_operator_index_for_key_group,
    key_group_range_for_operator,
)
from flink_tpu.core.config import Configuration, ConfigOption  # noqa: F401
from flink_tpu.core.types import RecordBatch, Schema, Field  # noqa: F401
from flink_tpu.core.time import TimeCharacteristic, TimeDomain, Watermark  # noqa: F401
