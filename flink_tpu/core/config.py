"""Configuration system.

Mirrors the contracts of the reference's string-keyed Configuration
(flink-core/.../configuration/Configuration.java:43) with typed ConfigOption
(ConfigOptions.java:53), re-done as plain Python. Loads ``flink-tpu-conf.yaml``
(a flat ``key: value`` file, like GlobalConfiguration.java:36 does for
flink-conf.yaml) without requiring a YAML dependency.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Generic, Optional, TypeVar

T = TypeVar("T")


@dataclass(frozen=True)
class ConfigOption(Generic[T]):
    key: str
    default: Optional[T] = None
    description: str = ""
    # declared value type; inferred from the default when omitted. An
    # option whose default is None (e.g. checkpoint.dir) can still
    # declare one, so conf-file strings coerce — and mis-parse loudly —
    # regardless of whether a default exists.
    type: Optional[type] = None

    def with_default(self, default: T) -> "ConfigOption[T]":
        return ConfigOption(self.key, default, self.description, self.type)

    def value_type(self) -> Optional[type]:
        if self.type is not None:
            return self.type
        if self.default is not None:
            return builtins_type(self.default)
        return None


def builtins_type(v) -> type:
    # bool before int: isinstance(True, int) holds, and a bool option
    # must parse "false" as False, not int("false")
    return bool if isinstance(v, bool) else type(v)


_TRUE = ("true", "1", "yes", "on")
_FALSE = ("false", "0", "no", "off")


def coerce_value(key: str, v: str, t: type):
    """Parse a conf-file string as declared type ``t``; failures name
    the config key (an anonymous ``ValueError: invalid literal`` from
    deep inside a job setup is undebuggable) and unrecognized boolean
    strings are REJECTED rather than silently mapped to False."""
    s = v.strip()
    if t is bool:
        low = s.lower()
        if low in _TRUE:
            return True
        if low in _FALSE:
            return False
        raise ValueError(
            f"config {key!r}: {v!r} is not a boolean "
            f"(expected one of {_TRUE + _FALSE})"
        )
    try:
        return t(s)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"config {key!r}: cannot parse {v!r} as {t.__name__}"
        ) from e


class Configuration:
    """String-keyed config map with typed accessors."""

    def __init__(self, data: Optional[dict] = None):
        self._data: dict[str, Any] = dict(data or {})

    # -- generic --------------------------------------------------------
    def set(self, key, value) -> "Configuration":
        self._data[key.key if isinstance(key, ConfigOption) else key] = value
        return self

    def get(self, option: ConfigOption, default=None):
        if option.key in self._data:
            v = self._data[option.key]
            # conf-file values arrive as STRINGS (the flat-yaml loader
            # stores text); coerce to the option's DECLARED type — not
            # the default's presence — so `parallelism.default: 4`
            # never leaks '4' into arithmetic and a default-None option
            # still parses (and mis-parses loudly, with the key named)
            t = option.value_type()
            if t is None and default is not None:
                t = builtins_type(default)
            if isinstance(v, str) and t is not None and t is not str:
                return coerce_value(option.key, v, t)
            return v
        return option.default if default is None else default

    def contains(self, option: ConfigOption) -> bool:
        return option.key in self._data

    # -- typed ----------------------------------------------------------
    def get_int(self, key: str, default: int = 0) -> int:
        return int(self._data.get(key, default))

    def get_float(self, key: str, default: float = 0.0) -> float:
        return float(self._data.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._data.get(key, default)
        if isinstance(v, str):
            return v.strip().lower() in ("true", "1", "yes")
        return bool(v)

    def get_str(self, key: str, default: str = "") -> str:
        return str(self._data.get(key, default))

    def to_dict(self) -> dict:
        return dict(self._data)

    def merge(self, other: "Configuration") -> "Configuration":
        out = Configuration(self._data)
        out._data.update(other._data)
        return out

    def __repr__(self):
        return f"Configuration({self._data!r})"


def load_global_configuration(conf_dir: Optional[str] = None) -> Configuration:
    """Load flink-tpu-conf.yaml from conf_dir (or $FLINK_TPU_CONF_DIR).

    Parses the flat `key: value` subset of YAML (comments with #), matching
    how the reference's GlobalConfiguration treats flink-conf.yaml.
    """
    conf_dir = conf_dir or os.environ.get("FLINK_TPU_CONF_DIR", "")
    cfg = Configuration()
    path = os.path.join(conf_dir, "flink-tpu-conf.yaml") if conf_dir else None
    if path and os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line or ":" not in line:
                    continue
                k, v = line.split(":", 1)
                cfg.set(k.strip(), v.strip())
    return cfg


class CoreOptions:
    """Registry of well-known options (ref ConfigConstants.java:29 role)."""

    DEFAULT_PARALLELISM = ConfigOption("parallelism.default", 1)
    MAX_PARALLELISM = ConfigOption("parallelism.max", 128)
    BATCH_SIZE = ConfigOption("execution.micro-batch-size", 8192)
    STATE_SLOTS_PER_SHARD = ConfigOption("state.backend.device.slots-per-shard", 1 << 16)
    STATE_PROBE_LENGTH = ConfigOption("state.backend.device.probe-length", 16)
    CHECKPOINT_INTERVAL_STEPS = ConfigOption("checkpoint.interval-steps", 0)
    CHECKPOINT_DIR = ConfigOption("checkpoint.dir", None, type=str)
    # snapshot strategy (flink_tpu/checkpointing, ref incremental RocksDB
    # checkpoints + asynchronous snapshots): "full" writes self-contained
    # snapshots, "incremental" writes delta checkpoints covering only the
    # dirty key groups, chained to a periodic full base via manifest.json
    CHECKPOINT_MODE = ConfigOption(
        "checkpoint.mode", "full",
        "full | incremental (changelog delta + manifest chain)")
    # serialize + write on the background materializer thread; the step
    # loop blocks only for the staging fetch. Defaults on for incremental.
    CHECKPOINT_ASYNC = ConfigOption(
        "checkpoint.async", False,
        "materialize checkpoints on a background thread")
    CHECKPOINT_RETAIN = ConfigOption(
        "checkpoint.retain", 2, "retained checkpoints (chain-closure aware)")
    CHECKPOINT_COMPACT_EVERY = ConfigOption(
        "checkpoint.compact-every", 8,
        "write a fresh full base after this many chained checkpoints")
    CHECKPOINT_STAGING_SLOTS = ConfigOption(
        "checkpoint.staging-slots", 2,
        "host staging buffers in flight (double-buffered by default)")
    # -- task-local snapshot cache (checkpointing/local.py, ref Flink
    # task-local recovery; docs/fault-tolerance.md) ---------------------
    CHECKPOINT_LOCAL_ENABLED = ConfigOption(
        "checkpoint.local.enabled", False,
        "mirror every published checkpoint into a host-local cache with "
        "per-blob checksums; restore prefers the verified local copy "
        "per chain member and falls back to primary on miss/corruption")
    CHECKPOINT_LOCAL_DIR = ConfigOption(
        "checkpoint.local.dir", None, type=str,
        description="task-local cache directory (node-local disk in "
        "production); default: a '<checkpoint.dir>-local' sibling")
    # -- recovery fast path (docs/fault-tolerance.md) -------------------
    RECOVERY_WARM_RESTART = ConfigOption(
        "recovery.warm-restart", True,
        "classify failures at the restart boundary and recover "
        "TRANSIENT host-side ones (watchdog trip, checkpoint budget "
        "exhaustion, DCN peer stall, ingest-thread death) in-process: "
        "live jitted kernels are reused (no recompile) and only the "
        "key groups dirty since the restored cut are re-staged when "
        "the cut's fire horizon still matches; off = every restart "
        "takes the full restore path")
    # -- elastic recovery (runtime/elastic.py; docs/fault-tolerance.md) -
    RECOVERY_ELASTIC = ConfigOption(
        "recovery.elastic", True,
        "re-plan the job at reduced parallelism when a mesh shard's "
        "device is lost (DeviceLostError / detected device loss): "
        "re-slice key-group ranges over the survivors, rebuild the "
        "compiled step family, rescaled-restore the last durable cut, "
        "and resume exactly-once in degraded mode; off = device loss "
        "takes the ordinary full-restore path at the original "
        "parallelism (which on real hardware fails until the device "
        "returns)")
    RECOVERY_MIN_SHARDS = ConfigOption(
        "recovery.min-shards", 1,
        "fewest surviving shards the elastic re-plan may degrade to; "
        "losing capacity below this floor FAILS the job instead of "
        "re-planning (capacity-critical jobs set it near the planned "
        "parallelism)")
    # -- pipelined ingest (runtime/ingest.py; docs/performance.md) ------
    # prep-half prefetch thread: poll + encode of batch k+1 overlaps the
    # device step of batch k. Checkpoint-compatible since the epoch-
    # tagged applied-offset cut — "auto" is on for every windowed stage.
    PIPELINE_PREFETCH = ConfigOption(
        "pipeline.prefetch", "auto",
        "auto | on | off — overlap source poll + host encode with device "
        "compute (off is the fully-serial escape hatch)")
    PIPELINE_MAX_INFLIGHT = ConfigOption(
        "pipeline.max-inflight-steps", 4,
        "bound on dispatched-but-unfinished update steps (caps the fire "
        "wait behind the device backlog)")
    PIPELINE_DEVICE_STAGING = ConfigOption(
        "pipeline.device-staging", "auto",
        "auto | on | off — pad + jax.device_put batches on the ingest "
        "thread (route-aware sharding) so the H2D transfer of batch k+1 "
        "overlaps the step of batch k; auto follows pipeline.prefetch")
    PIPELINE_STAGING_RING = ConfigOption(
        "pipeline.staging-ring-depth", 2,
        "preallocated host padding buffers recycled by the ingest "
        "thread (2 = double-buffered)")
    PIPELINE_PREFETCH_DEPTH = ConfigOption(
        "pipeline.prefetch-depth", 2,
        "prepped batches the ingest queue holds ahead of the step loop")
    # -- dispatch fusion + pre-combine (docs/performance.md) ------------
    PIPELINE_STEPS_PER_DISPATCH = ConfigOption(
        "pipeline.steps-per-dispatch", 1,
        "K staged micro-batches fused into ONE jitted lax.scan megastep "
        "dispatch; divides the fixed per-dispatch cost (Python, tracing, "
        "and the ~100ms tunnel round trip) by K at the cost of K-batch "
        "fire/checkpoint granularity. 1 = unfused (bit-identical "
        "single-step dispatch)")
    UPDATE_PRECOMBINE = ConfigOption(
        "pipeline.update-precombine", "auto",
        "auto | on | off — collapse duplicate (slot, pane) scatter keys "
        "with one shared sort + segmented scan before the state scatter "
        "(built-in reducers; duplicate scatter indices serialize on "
        "TPU). auto enables it on accelerator backends and keeps the "
        "CPU path unsorted (XLA's CPU sort costs more than the CPU "
        "scatter it saves — measured in device_update_ceiling)")
    PIPELINE_FUSED_FIRE = ConfigOption(
        "pipeline.fused-fire", "auto",
        "auto | on | off — fold the fire sweep into the K-fused megastep "
        "scan (the resident pipeline, ISSUE 7): a pane-boundary crossing "
        "inside a K-group fires WITHIN the scan instead of breaking the "
        "group and paying a separate fire dispatch; fire payloads "
        "surface as lagged megastep outputs. auto = on whenever "
        "steps-per-dispatch > 1; off keeps the split-dispatch path "
        "(which always remains the fallback for partial groups and the "
        "DCN lockstep plane)")
    PIPELINE_RESIDENT_LOOP = ConfigOption(
        "pipeline.resident-loop", "auto",
        "auto | on | while | off — the device-resident steady-state "
        "loop (ISSUE 12): the prefetch thread publishes staged batches "
        "into an HBM batch ring and the step loop dispatches ONE jitted "
        "drain over every ready slot (fused update+fire per slot, "
        "count-gated), so steady state costs one host round trip per "
        "ring drain instead of one per megastep. Requires prefetch + "
        "device staging + fused fire; exactly-once cuts move to "
        "ring-drain boundaries. auto = on whenever the fused-fire "
        "resident pipeline is active on a single-controller topology. "
        "while (ISSUE 20) swaps the count-gated scan for an early-exit "
        "lax.while_loop whose condition re-reads the ring's HBM publish "
        "cursor, so a batch published mid-drain retires in the SAME "
        "dispatch (bounded by pipeline.while-drain.max-slots); CPU "
        "backends keep the scan drain (no-aliasing platform gate — see "
        "pipeline.while-drain.cpu-override). DCN coordinator jobs "
        "compose per-host: on/while run the host-local resident drain "
        "between lockstep exchange boundaries (ISSUE 20b)")
    PIPELINE_RING_DEPTH = ConfigOption(
        "pipeline.ring-depth", 16,
        "HBM slots in the device batch ring (pipeline.resident-loop): "
        "bounds device-resident batches AND the max slots one drain "
        "dispatch consumes — deeper rings amortize the host round trip "
        "further but coarsen fire/checkpoint latency and HBM residency")
    PIPELINE_WHILE_DRAIN_MAX_SLOTS = ConfigOption(
        "pipeline.while-drain.max-slots", 0,
        "per-dispatch slot bound for pipeline.resident-loop=while: the "
        "while-drain retires at most this many ring slots in one "
        "dispatch regardless of how many publishes land mid-drain, so "
        "the exactly-once cut, the watchdog deadline (armed at the "
        "BOUND, not the observed fill), and the flight-recorder payload "
        "[n_shards, max_slots, 9] stay well-defined. 0 (default) sizes "
        "it to 2 x pipeline.ring-depth, never below ring-depth")
    PIPELINE_WHILE_DRAIN_CPU_OVERRIDE = ConfigOption(
        "pipeline.while-drain.cpu-override", "off",
        "on | off — run the while-drain kernel on CPU backends despite "
        "the platform gate (CPU buffer donation does not alias, so the "
        "cursor freezes at its dispatch snapshot and the while drain "
        "degrades to exactly the scan drain's count gating). Test/bench "
        "escape hatch; production CPU runs keep the scan drain")
    PIPELINE_DATA_PARALLEL = ConfigOption(
        "pipeline.data-parallel", "auto",
        "auto | on | off — mesh-resident data parallelism (ISSUE 13): "
        "each chip owns a contiguous key-group slice, the prefetch "
        "thread routes records to the owning shard off-loop and "
        "publishes into that shard's slice of a sharded device batch "
        "ring, and ONE shard_map'd drain dispatch advances every "
        "shard's ring concurrently with zero cross-chip collectives on "
        "the keyed hot path (fires pack per-shard and merge host-side "
        "on the lagged consume path). Requires the resident loop; "
        "batches whose per-shard skew overflows the ring slice fall "
        "back to the replicated mask route for that batch only. auto = "
        "on whenever the resident loop is active on a multi-chip mesh")
    PIPELINE_SHARD_CAPACITY_FACTOR = ConfigOption(
        "pipeline.shard-capacity-factor", 2.0,
        "per-shard ring-slice rows as a multiple of the uniform share "
        "B/n_shards (pipeline.data-parallel): headroom for key-group "
        "skew before a batch falls back to the replicated route — "
        "larger tolerates hotter shards at the cost of HBM and padded "
        "drain work")
    PIPELINE_STAGES_EXCHANGE_LANES = ConfigOption(
        "pipeline.stages.exchange-lanes", 1024,
        "chained stage graphs (runtime/stages.py, ISSUE 16): lanes of "
        "the on-device inter-stage exchange — the packed fire rows one "
        "drain slot may hand from stage N to stage N+1. Sized above "
        "fires-per-step x the per-fire key population the upstream "
        "stage can emit; overrun counts into the DOWNSTREAM stage's "
        "dropped_capacity (strict capacity surfaces it)")
    PIPELINE_STAGES_MAX_STAGES = ConfigOption(
        "pipeline.stages.max-stages", 4,
        "chained stage graphs: maximum keyed windowed stages one job "
        "may chain through the resident drain. Each stage adds its own "
        "table+ring state and per-slot update+fire work to the ONE "
        "drain dispatch; the cap keeps a pathological deep chain a "
        "loud setup error instead of an HBM surprise")
    STATE_PACKED_PLANES = ConfigOption(
        "state.packed-planes", "auto",
        "auto | on | off — store the touched (fire-eligibility) bits as "
        "a trailing column of the pane accumulator so the update issues "
        "ONE scatter over wider lanes and ring-reset/purge sweeps clear "
        "one plane instead of two (built-in reducers with default "
        "neutrals only). auto enables it on accelerator backends where "
        "scatter passes dominate; CPU keeps split planes (the wider "
        "sweep costs more than the scatter it saves — measured in "
        "device_update_ceiling)")
    # tiered key-group state (round 18): HBM-resident hot set over the
    # host spill tier, watermark-driven prefetch (docs/state-tiers.md)
    STATE_TIERS_RESIDENT_KEY_GROUPS = ConfigOption(
        "state.tiers.resident-key-groups", 0,
        "key-groups kept HBM-resident per shard (0 = tiering off, every "
        "group resident). Cold groups demote to the host spill tier and "
        "promote back ahead of their predicted next fire; a batch "
        "routing into a non-resident group rides the overflow ring for "
        "that batch only (never lossy, counted in tier_faults). "
        "Requires a spill-tier-eligible stage (builtin float32 reduce, "
        "allowed lateness 0, no chained stages) with an overflow ring")
    STATE_TIERS_PREFETCH_AHEAD_PANES = ConfigOption(
        "state.tiers.prefetch-ahead-panes", 2,
        "promote a cold key-group once its earliest pending pane is "
        "within this many panes of the watermark — the window fire it "
        "predicts then comes off the device instead of a host merge")
    STATE_TIERS_MIN_DWELL_CYCLES = ConfigOption(
        "state.tiers.min-dwell-cycles", 4,
        "poll cycles a key-group must stay in its tier before the "
        "ranker may flip it again (hysteresis against promote/demote "
        "thrash; an imminent-fire promote overrides it)")
    STATE_TIERS_MAX_SWAPS_PER_CYCLE = ConfigOption(
        "state.tiers.max-swaps-per-cycle", 0,
        "cap on tier promote+demote moves one poll cycle may splice "
        "(0 = unlimited); a working-set shift bigger than the cap "
        "carries the remainder to the next cycle instead of stalling "
        "the step loop behind one giant swap burst")
    RESTART_STRATEGY = ConfigOption("restart-strategy", "none")
    RESTART_ATTEMPTS = ConfigOption("restart-strategy.fixed-delay.attempts", 3)
    RESTART_DELAY_S = ConfigOption("restart-strategy.fixed-delay.delay", 0.0)
    RESTART_FAILURE_RATE_MAX = ConfigOption(
        "restart-strategy.failure-rate.max-failures", 3)
    RESTART_FAILURE_RATE_INTERVAL = ConfigOption(
        "restart-strategy.failure-rate.interval", 60.0)
    RESTART_FAILURE_RATE_DELAY = ConfigOption(
        "restart-strategy.failure-rate.delay", 0.0)
    # exponential-backoff restart strategy (ref RestartStrategies.
    # exponentialDelayRestart): delay doubles per consecutive failure up
    # to max-delay, a quiet period resets it, jitter decorrelates
    # restart storms across jobs. Restarts are unbounded like the
    # reference — the growing delay is the budget.
    RESTART_EXP_INITIAL_DELAY = ConfigOption(
        "restart-strategy.exponential-backoff.initial-delay", 1.0,
        "seconds before the first restart attempt")
    RESTART_EXP_MAX_DELAY = ConfigOption(
        "restart-strategy.exponential-backoff.max-delay", 60.0,
        "ceiling (s) the growing delay never exceeds")
    RESTART_EXP_MULTIPLIER = ConfigOption(
        "restart-strategy.exponential-backoff.multiplier", 2.0,
        "delay growth factor per consecutive failure")
    RESTART_EXP_JITTER = ConfigOption(
        "restart-strategy.exponential-backoff.jitter", 0.1,
        "+- fraction of the delay drawn uniformly at random")
    RESTART_EXP_RESET_AFTER = ConfigOption(
        "restart-strategy.exponential-backoff.reset-after", 3600.0,
        "a failure-free quiet period (s) this long resets the delay "
        "back to initial-delay")
    # -- failure containment (docs/fault-tolerance.md) ------------------
    # checkpoint failure budget (checkpointing/policy.py, ref
    # CheckpointFailureManager): a failed/timed-out checkpoint is
    # aborted + counted; only exhausting the consecutive-failure budget
    # escalates to the restart strategy
    CHECKPOINT_TOLERABLE_FAILURES = ConfigOption(
        "checkpoint.tolerable-failures", 0,
        "consecutive checkpoint failures tolerated (aborted + counted) "
        "before escalating to the restart strategy; 0 = the first "
        "failure escalates (the pre-budget behavior)")
    CHECKPOINT_TIMEOUT = ConfigOption(
        "checkpoint.timeout", 600.0,
        "seconds an async checkpoint may stay unpublished after its "
        "barrier before it is declared failed (its publish is "
        "cancelled and the failure counts against the budget)")
    CHECKPOINT_MIN_PAUSE = ConfigOption(
        "checkpoint.min-pause", 0.0,
        "minimum pause in seconds between the end of one checkpoint "
        "attempt and the next trigger")
    # step-loop watchdog (runtime/watchdog.py): per-phase deadlines that
    # convert a distributed hang into a clean, attributed job failure
    WATCHDOG_ENABLED = ConfigOption(
        "watchdog.enabled", True,
        "supervise step-loop phases; a phase overrunning its deadline "
        "raises an attributed WatchdogError in the step loop")
    WATCHDOG_INTERVAL = ConfigOption(
        "watchdog.interval", 1.0, "watchdog check period in seconds")
    WATCHDOG_SOURCE_TIMEOUT = ConfigOption(
        "watchdog.source-timeout", 0.0,
        "deadline (s) on the ingest wait per cycle; 0 disables — a "
        "legitimate source may idle indefinitely")
    WATCHDOG_FIRE_TIMEOUT = ConfigOption(
        "watchdog.fire-timeout", 600.0,
        "deadline (s) on one fire-step dispatch")
    WATCHDOG_FETCH_TIMEOUT = ConfigOption(
        "watchdog.fetch-timeout", 600.0,
        "deadline (s) on the barrier device fetch")
    WATCHDOG_CKPT_SYNC_TIMEOUT = ConfigOption(
        "watchdog.checkpoint-sync-timeout", 600.0,
        "deadline (s) on a checkpoint's synchronous phase")
    WATCHDOG_SLOT_TIMEOUT = ConfigOption(
        "watchdog.slot-timeout", 600.0,
        "deadline (s) on the materializer staging-slot wait")
    WATCHDOG_DRAIN_TIMEOUT = ConfigOption(
        "watchdog.drain-timeout", 120.0,
        "PER-SLOT deadline (s) on one resident ring-drain dispatch "
        "(pipeline.resident-loop); armed scaled by the slot count the "
        "drain consumes, so deep drains get proportionally more time. "
        "0 disables")
    WATCHDOG_RESTORE_TIMEOUT = ConfigOption(
        "watchdog.restore-timeout", 900.0,
        "deadline (s) on a whole checkpoint restore; the step-loop "
        "phase deadlines are suspended while a restore runs, so a "
        "legitimately long cold restore cannot trip a steady-state "
        "deadline mid-recovery. 0 disables")
    # -- observability (docs/observability.md) --------------------------
    # step-loop span tracing: bounded ring of phase spans exported as
    # Chrome-trace JSON via /jobs/<jid>/traces (metrics/tracing.py)
    TRACING = ConfigOption(
        "observability.tracing", False,
        "record step-loop phase spans (off by default; negligible when "
        "sampled)")
    TRACE_SAMPLE_EVERY = ConfigOption(
        "observability.trace-sample-every", 1,
        "record spans for every N-th poll cycle only")
    TRACE_BUFFER_SPANS = ConfigOption(
        "observability.trace-buffer-spans", 65536,
        "span ring-buffer capacity (old spans fall off)")
    TRACE_DUMP = ConfigOption(
        "observability.trace-dump", "",
        "write the Chrome-trace JSON to this file when the job ends "
        "(empty = don't)")
    KG_STATS = ConfigOption(
        "observability.kg-stats", None,
        "enable key-group skew telemetry (per-batch fill scatter in the "
        "compiled step + the occupancy kernel at fire boundaries); "
        "defaults to whatever observability.tracing is — off means the "
        "steps compile without any telemetry work")
    KG_STATS_INTERVAL_MS = ConfigOption(
        "observability.kg-stats-interval-ms", 1000,
        "min interval between per-key-group occupancy kernel runs "
        "(refreshed at fire boundaries)")
    DRAIN_STATS = ConfigOption(
        "observability.drain-stats", None,
        "enable the drain-interior flight recorder (per-slot x per-shard "
        "counters stacked inside the resident/sharded ring-drain scan, "
        "unpacked lagged into occupancy/duty-cycle/latency telemetry); "
        "defaults to whatever observability.tracing is — off means the "
        "drain kernels compile without any telemetry work (ledger-"
        "verified byte-identical)")
    DRAIN_STATS_EVERY = ConfigOption(
        "observability.drain-stats-every", 8,
        "fetch the drain-stats payload to the host every N-th drain "
        "dispatch only (the device computes it every drain when the "
        "recorder is compiled in; duty-cycle/occupancy EWMAs update on "
        "every drain regardless). 1 = every drain")
    COMPILE_COST = ConfigOption(
        "observability.compile-cost", False,
        "record XLA cost_analysis (FLOPs/bytes) of the update step at "
        "warmup — costs one extra trace+compile")
    KG_HEAT_ALPHA = ConfigOption(
        "observability.kg-heat-alpha", 0.05,
        "EWMA smoothing factor for the per-key-group heat series the "
        "flight recorder folds the sampled kg-fill counters into "
        "(higher = faster reaction, noisier heat); needs "
        "observability.kg-stats")
    DOCTOR = ConfigOption(
        "observability.doctor", True,
        "enable the pipeline doctor (metrics/doctor.py): a pure "
        "host-side rule engine joining the telemetry planes into "
        "ranked findings with evidence + config remedies, served at "
        "/jobs/<jid>/doctor and `python -m flink_tpu.doctor`")
    DOCTOR_STARVED_THRESHOLD = ConfigOption(
        "observability.doctor.starved-threshold", 0.5,
        "ring-starved EWMA fraction above which the doctor reports a "
        "ring-starved finding (publish side cannot keep the drain fed)")
    DOCTOR_SATURATED_THRESHOLD = ConfigOption(
        "observability.doctor.saturated-threshold", 0.9,
        "drain duty-cycle EWMA above which the doctor reports a "
        "device-saturated finding (every drain retires a full ring)")
    DOCTOR_EDGE_UTILIZATION_THRESHOLD = ConfigOption(
        "observability.doctor.edge-utilization-threshold", 0.8,
        "peak inter-stage edge demand / pipeline.stages.exchange-lanes "
        "ratio above which the doctor warns the edge is near overflow")
    DOCTOR_KG_SKEW_THRESHOLD = ConfigOption(
        "observability.doctor.kg-skew-threshold", 4.0,
        "key-group heat max/mean ratio above which the doctor flags a "
        "shard re-slice candidate")
    DOCTOR_TIER_CHURN_THRESHOLD = ConfigOption(
        "observability.doctor.tier-churn-threshold", 0.5,
        "tier swaps (promotes+demotes) per resident drain above which "
        "the doctor reports tier-thrash (the residency budget is "
        "fighting the working set)")
    DOCTOR_TIER_MISS_THRESHOLD = ConfigOption(
        "observability.doctor.tier-miss-threshold", 0.5,
        "prefetch-miss fraction (misses / (hits+misses)) above which "
        "the doctor reports tier-thrash — promotions arrive after the "
        "traffic they predicted")
    DOCTOR_RECOMPILE_THRESHOLD = ConfigOption(
        "observability.doctor.recompile-threshold", 8,
        "steady-state XLA compiles beyond which the doctor reports a "
        "recompile storm (steady state should dispatch pre-compiled "
        "steps only)")
    # -- self-tuning runtime controller (runtime/controller.py,
    # docs/self-tuning.md): closed loop over the doctor's findings +
    # the raw regime/heat planes, serviced at the poll-cycle seam ------
    CONTROLLER_ENABLED = ConfigOption(
        "controller.enabled", False,
        "enable the self-tuning RuntimeController: bounded hill-climb "
        "over the declared hot knobs keyed on the observed regime, "
        "plus live heat-balanced key-group rebalancing through the "
        "savepoint-cut rescale. Off (the default) constructs nothing "
        "and adds zero work to any path")
    CONTROLLER_INTERVAL_CYCLES = ConfigOption(
        "controller.interval-cycles", 16,
        "poll cycles between controller decisions; each decision "
        "applies at most one knob move or one rebalance, so the "
        "interval is also the minimum spacing between actuations")
    CONTROLLER_REVERT_THRESHOLD = ConfigOption(
        "controller.revert-threshold", 0.05,
        "fractional worsening of the tracked metric (events/s) within "
        "the probation window that auto-reverts a knob move; the "
        "reverted (knob, direction) then sits out a cooldown")
    CONTROLLER_PROBATION_CYCLES = ConfigOption(
        "controller.probation-cycles", 16,
        "poll cycles a knob move stays on probation: the controller "
        "compares the tracked metric before vs after and reverts past "
        "controller.revert-threshold; no new move starts meanwhile")
    CONTROLLER_COOLDOWN_CYCLES = ConfigOption(
        "controller.cooldown-cycles", 64,
        "poll cycles a reverted (knob, direction) pair is barred from "
        "being retried (keeps the hill-climb from oscillating on a "
        "knob the workload has already voted down)")
    CONTROLLER_REBALANCE_THRESHOLD = ConfigOption(
        "controller.rebalance-threshold", 4.0,
        "per-shard key-group heat skew (hottest shard / mean shard "
        "heat) above which the controller considers a live "
        "heat-balanced re-slice of the shard ranges")
    CONTROLLER_MIN_REBALANCE_INTERVAL = ConfigOption(
        "controller.min-rebalance-interval", 30.0,
        "seconds between live rebalances: each one is a savepoint-cut "
        "rescale (flush + snapshot + re-plan + restore), so the rate "
        "limit bounds how much of the job's time rebalancing may eat")
    CONTROLLER_MIN_GAIN = ConfigOption(
        "controller.min-gain", 1.2,
        "predicted imbalance improvement (current hottest-shard heat / "
        "rebalanced hottest-shard heat) a re-slice must clear before "
        "the controller pays for a live rescale; gains under it are "
        "skipped and ledgered as such")
    # -- state backend / keying (docs/performance.md) -------------------
    # The keys below predate the config-hygiene lint (ISSUE 9): they
    # were read as bare literals across the executor; declaring them
    # here is what gives them strict coercion, a single default, and a
    # docs anchor.
    STATE_LAYOUT = ConfigOption(
        "state.backend.layout", "auto",
        "auto | hash | direct — slot layout of the device state table; "
        "direct (slot == key) skips probing for bounded non-negative "
        "int keys, auto picks per job")
    STATE_OVERFLOW_RING = ConfigOption(
        "state.backend.overflow-ring", -1,
        "overflow-ring rows per shard for spillable reduces; -1 = "
        "auto-size from the monitoring lag, 0 disables the ring")
    STATE_STAGE_PROBE_LEN = ConfigOption(
        "state.probe-len", 16,
        "open-addressing probe length of a keyed stage's slot table "
        "(the per-stage override of "
        "state.backend.device.probe-length)")
    STATE_STRICT_CAPACITY = ConfigOption(
        "state.backend.strict-capacity", True,
        "fail the job when records would be dropped (capacity "
        "overflow) rather than tolerate loss")
    KEYS_REVERSE_MAP = ConfigOption(
        "keys.reverse-map", True,
        "keep the host-side hash->original-key reverse map so fired "
        "windows surface user keys; off saves host memory when sinks "
        "only need hashes")
    # -- mesh exchange route (docs/performance.md) ----------------------
    EXCHANGE_MODE = ConfigOption(
        "exchange.mode", "auto",
        "auto | all_to_all | mask — how records reach their owning "
        "shard: per-batch adaptive all_to_all (auto), always exchange, "
        "or always replicate-and-mask")
    EXCHANGE_CAPACITY_FACTOR = ConfigOption(
        "exchange.capacity-factor", 2.0,
        "per-shard exchange bucket headroom over the balanced share "
        "(hash skew beyond it falls back / counts dropped_capacity)")
    # -- windowing ------------------------------------------------------
    WINDOW_RING_PANES = ConfigOption(
        "window.ring-panes", 0,
        "pane ring size override; 0 = auto from window spec + "
        "out-of-orderness")
    WINDOW_FIRES_PER_STEP = ConfigOption(
        "window.fires-per-step", 4,
        "window ends evaluated per fire step")
    # -- cross-host DCN plane (docs/DCN_INGESTION.md) -------------------
    DCN_COORDINATOR = ConfigOption(
        "dcn.coordinator", "",
        "host:port of the jax.distributed coordinator; non-empty "
        "switches the executor to the multi-process DCN plane")
    DCN_NUM_PROCESSES = ConfigOption(
        "dcn.num-processes", 1, "process count of the DCN job")
    DCN_PROCESS_ID = ConfigOption(
        "dcn.process-id", 0, "this process's index in the DCN job")
    DCN_ORIGIN_MS = ConfigOption(
        "dcn.origin-ms", 0,
        "shared time-domain origin (epoch ms) so every process buckets "
        "event time identically")
    DCN_REBALANCE_ADDRS = ConfigOption(
        "dcn.rebalance-addrs", "",
        "comma-separated host:port per process for the work-stealing "
        "rebalance ring side channel")
    DCN_INGEST_PARTITIONER = ConfigOption(
        "dcn.ingest-partitioner", "forward",
        "forward | rebalance — whether each process keeps its source "
        "partition or steals from neighbors over the rebalance ring")
    # -- CEP acceleration -----------------------------------------------
    CEP_DEVICE_ENABLED = ConfigOption(
        "cep.device.enabled", True,
        "compile eligible CEP patterns to the device NFA kernel; off "
        "forces the host interpreter")
    CEP_DEVICE_WITHIN_BUCKETS = ConfigOption(
        "cep.device.within-buckets", 8,
        "time-bucket count for the device NFA's within-window pruning")
    # -- control plane / cluster (docs/DEPLOYMENT.md) -------------------
    CONTROLLER_RPC_PORT = ConfigOption(
        "controller.rpc.port", 6123,
        "control-plane RPC port (the jobmanager.rpc.port analog); "
        "0 = ephemeral")
    CONTROLLER_BIND_HOST = ConfigOption(
        "controller.bind-host", "127.0.0.1", "control-plane bind host")
    HA_DIR = ConfigOption(
        "high-availability.dir", None,
        "file-lock leader-election directory (the ZooKeeper-quorum "
        "analog); unset = standalone", type=str)
    SECURITY_AUTH_TOKEN = ConfigOption(
        "security.auth.token", "",
        "shared-secret token for the control plane + HTTP monitor; "
        "empty = open cluster")
    SECURITY_AUTH_TOKEN_FILE = ConfigOption(
        "security.auth.token-file", "",
        "file to read the shared-secret token from (wins over env)")
    METRICS_REPORTERS = ConfigOption(
        "metrics.reporters", "",
        "comma-separated reporter names; each configures via "
        "metrics.reporter.<name>.* keys")
