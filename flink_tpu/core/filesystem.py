"""FileSystem abstraction — the flink-core FileSystem SPI (SURVEY §2.1,
ref org.apache.flink.core.fs.FileSystem: scheme-dispatched get(), local +
pluggable remote implementations).

Paths carry a scheme (``file:///tmp/x``, ``mem://bucket/x``; bare paths
default to ``file``). `get_filesystem(path)` dispatches on the scheme;
implementations cover the operations the framework's file connectors and
storage need. A process-local in-memory filesystem ships for tests and
as the template for remote implementations (the image has no HDFS/S3
client — the SPI is the extension seam, like the reference's
HadoopFileSystem wrapper).
"""

from __future__ import annotations

import io
import os
import threading
from typing import Dict, List, Tuple

from flink_tpu.testing import faults


def split_scheme(path: str) -> Tuple[str, str]:
    if "://" in path:
        scheme, rest = path.split("://", 1)
        return scheme, rest
    return "file", path


class FileSystem:
    """SPI: the operation set the framework's connectors/storage use."""

    def open(self, path: str, mode: str = "rb", newline=None):
        """newline follows builtins.open semantics (pass "" for csv);
        in-memory implementations that do no newline translation may
        ignore it."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def list_dir(self, path: str) -> List[str]:
        raise NotImplementedError

    def mkdirs(self, path: str):
        raise NotImplementedError

    def delete(self, path: str, recursive: bool = False):
        raise NotImplementedError

    def rename(self, src: str, dst: str):
        raise NotImplementedError

    def size(self, path: str) -> int:
        raise NotImplementedError


class LocalFileSystem(FileSystem):
    def open(self, path: str, mode: str = "rb", newline=None):
        if "w" in mode or "a" in mode:
            # chaos seam: transient filesystem write failures inject at
            # the SPI boundary every connector/storage write crosses
            faults.inject("fs.open", path=path, mode=mode)
        if "b" in mode:
            return open(path, mode)
        return open(path, mode, newline=newline)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def list_dir(self, path: str) -> List[str]:
        return sorted(os.listdir(path))

    def mkdirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str, recursive: bool = False):
        if os.path.isdir(path):
            if recursive:
                import shutil

                shutil.rmtree(path)
            else:
                os.rmdir(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src: str, dst: str):
        os.replace(src, dst)

    def size(self, path: str) -> int:
        return os.path.getsize(path)


class MemoryFileSystem(FileSystem):
    """Process-local FS (the reference's testing filesystems' role, and
    the remote-implementation template: every op goes through the same
    SPI a real object store would)."""

    def __init__(self):
        self._files: Dict[str, bytes] = {}
        self._dirs = {""}
        self._lock = threading.Lock()

    class _Writer(io.BytesIO):
        def __init__(self, fs, path, text):
            super().__init__()
            self._fs, self._path, self._text = fs, path, text

        def write(self, data):  # type: ignore[override]
            if self._text and isinstance(data, str):
                data = data.encode()
            return super().write(data)

        def close(self):
            if self.closed:        # IOBase contract: close() repeatable
                return
            with self._fs._lock:
                self._fs._files[self._path] = self.getvalue()
            super().close()

    def open(self, path: str, mode: str = "rb", newline=None):
        # StringIO below performs no newline translation, so the csv
        # module's newline="" requirement is inherently satisfied
        text = "b" not in mode
        if "w" in mode or "a" in mode:
            faults.inject("fs.open", path=path, mode=mode)
            w = MemoryFileSystem._Writer(self, path, text)
            if "a" in mode:
                with self._lock:
                    existing = self._files.get(path)
                if existing is not None:
                    io.BytesIO.write(w, existing)
            return w
        with self._lock:
            if path not in self._files:
                raise FileNotFoundError(path)
            data = self._files[path]
        if text:
            return io.StringIO(data.decode(), newline=newline)
        return io.BytesIO(data)

    def exists(self, path: str) -> bool:
        with self._lock:
            return (
                path in self._files
                or path in self._dirs
                or any(f.startswith(path.rstrip("/") + "/")
                       for f in self._files)
            )

    def list_dir(self, path: str) -> List[str]:
        prefix = path.rstrip("/") + "/" if path else ""
        out = set()
        with self._lock:
            for f in self._files:
                if f.startswith(prefix):
                    out.add(f[len(prefix):].split("/")[0])
        return sorted(out)

    def mkdirs(self, path: str):
        with self._lock:
            parts = path.rstrip("/").split("/")
            for i in range(1, len(parts) + 1):   # parents too (os.makedirs)
                self._dirs.add("/".join(parts[:i]))

    def delete(self, path: str, recursive: bool = False):
        with self._lock:
            prefix = path.rstrip("/") + "/"
            children = [f for f in self._files if f.startswith(prefix)]
            if children and not recursive:
                # match LocalFileSystem: os.rmdir refuses non-empty dirs
                raise OSError(f"directory not empty: {path!r}")
            self._files.pop(path, None)
            self._dirs.discard(path.rstrip("/"))
            if recursive:
                for f in children:
                    del self._files[f]

    def rename(self, src: str, dst: str):
        with self._lock:
            if src in self._files:
                self._files[dst] = self._files.pop(src)
                return
            # directory rename: move every child under the prefix
            prefix = src.rstrip("/") + "/"
            children = [f for f in self._files if f.startswith(prefix)]
            if not children and src.rstrip("/") not in self._dirs:
                raise FileNotFoundError(src)   # match os.replace
            for f in children:
                self._files[dst.rstrip("/") + "/" + f[len(prefix):]] =                     self._files.pop(f)
            self._dirs.discard(src.rstrip("/"))
            self._dirs.add(dst.rstrip("/"))

    def size(self, path: str) -> int:
        with self._lock:
            return len(self._files[path])


_REGISTRY: Dict[str, FileSystem] = {
    "file": LocalFileSystem(),
    "mem": MemoryFileSystem(),
}


def register_filesystem(scheme: str, fs: FileSystem):
    """ref FileSystem factory registration (pluggable schemes)."""
    _REGISTRY[scheme] = fs


def get_filesystem(path: str) -> Tuple[FileSystem, str]:
    """path -> (filesystem, scheme-stripped path)."""
    scheme, rest = split_scheme(path)
    try:
        return _REGISTRY[scheme], rest
    except KeyError:
        raise ValueError(
            f"no filesystem registered for scheme {scheme!r} "
            f"(have: {sorted(_REGISTRY)})"
        ) from None
