"""Version compatibility shims for the jax API surface.

One seam for symbols that have moved between jax releases, so a jax bump
breaks loudly HERE (guarded by tests/test_compat.py) instead of at six
scattered import sites.

``shard_map``: promoted from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` in jax 0.6; ``from jax import shard_map``
therefore fails on the 0.4.x line this repo pins. The replication-check
kwarg was also renamed (``check_rep`` -> ``check_vma``), so the shim
normalizes to the NEW spelling: callers write ``check_vma`` and the shim
translates for an older jax.
"""

from __future__ import annotations

import functools
import inspect

import jax

if hasattr(jax, "shard_map"):  # jax >= 0.6: stable top-level export
    _shard_map = jax.shard_map
else:  # jax 0.4.x/0.5.x: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

# kwarg normalization applies to EITHER origin: the top-level promotion
# and the check_rep->check_vma rename did not ship in the same release,
# so the resolved symbol's own signature decides
if "check_vma" in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

__all__ = ["shard_map"]
