"""Typed record batches — the unit of data flow.

The reference moves one serialized record at a time through Netty buffers
(SpanningRecordSerializer; StreamRecord wrappers, SURVEY §2.3/§3.2). The
TPU-native unit is instead a fixed-width **struct-of-arrays micro-batch**: a
dict of equally-sized columns plus a validity mask and optional timestamps.
Fixed shapes keep XLA compilation stable; invalid lanes are padding.

RecordBatch is a registered pytree so it can flow through jit/shard_map.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from flink_tpu.ops.hashing import hash64_host, key_identity64  # noqa: F401


@dataclass(frozen=True)
class Field:
    name: str
    dtype: Any  # numpy dtype-like
    shape: Tuple[int, ...] = ()  # per-record trailing shape


@dataclass(frozen=True)
class Schema:
    fields: Tuple[Field, ...]

    @staticmethod
    def of(**kwargs) -> "Schema":
        return Schema(tuple(Field(k, v) for k, v in kwargs.items()))

    def names(self):
        return [f.name for f in self.fields]


@jax.tree_util.register_pytree_node_class
@dataclass
class RecordBatch:
    """Fixed-size columnar micro-batch.

    columns:    name -> array [B, ...]
    valid:      bool [B] — lanes carrying real records
    timestamps: int32 [B] event-time ticks (or None)
    key_hi/key_lo: uint32 [B] — 64-bit key identity, set after `keyBy`
    """

    columns: Dict[str, Any]
    valid: Any
    timestamps: Optional[Any] = None
    key_hi: Optional[Any] = None
    key_lo: Optional[Any] = None

    @property
    def size(self) -> int:
        return int(self.valid.shape[0])

    def with_columns(self, **cols) -> "RecordBatch":
        new = dict(self.columns)
        new.update(cols)
        return RecordBatch(new, self.valid, self.timestamps, self.key_hi, self.key_lo)

    def col(self, name: str):
        return self.columns[name]

    # -- pytree ---------------------------------------------------------
    def tree_flatten(self):
        keys = sorted(self.columns)
        children = tuple(self.columns[k] for k in keys) + (
            self.valid,
            self.timestamps,
            self.key_hi,
            self.key_lo,
        )
        return children, tuple(keys)

    @classmethod
    def tree_unflatten(cls, keys, children):
        cols = dict(zip(keys, children[: len(keys)]))
        valid, ts, hi, lo = children[len(keys):]
        return cls(cols, valid, ts, hi, lo)


def make_batch(
    columns: Dict[str, np.ndarray],
    batch_size: int,
    timestamps: Optional[np.ndarray] = None,
) -> RecordBatch:
    """Pad host columns up to batch_size and build the validity mask."""
    n = len(next(iter(columns.values())))
    if n > batch_size:
        raise ValueError(f"{n} records exceed batch size {batch_size}")
    out = {}
    for name, arr in columns.items():
        arr = np.asarray(arr)
        pad = np.zeros((batch_size - n,) + arr.shape[1:], dtype=arr.dtype)
        out[name] = np.concatenate([arr, pad], axis=0)
    valid = np.zeros(batch_size, dtype=bool)
    valid[:n] = True
    ts = None
    if timestamps is not None:
        ts = np.zeros(batch_size, dtype=np.int32)
        ts[:n] = np.asarray(timestamps, dtype=np.int32)
    return RecordBatch(out, valid, ts)


class KeyCodec:
    """Maps arbitrary host keys <-> 64-bit device key identities.

    Numeric keys map to their raw 64-bit bits (collision-free identity;
    device-side probe/route hashes do the mixing — see
    hashing.key_identity64); other keys via a cached per-object stable
    hash. Keeps the reverse map so fired windows can be reported with
    original keys (the device only ever sees the 64-bit id).
    """

    def __init__(self):
        self._rev: dict[int, Any] = {}
        # encode may run on the ingest prefetch thread while a checkpoint
        # lists newly-seen keys on the step-loop thread (runtime/ingest):
        # the lock makes the per-batch insert burst and the keymap-log
        # slice atomic against each other (one acquisition per BATCH, not
        # per key — negligible against the encode itself)
        self._lock = threading.Lock()

    def encode(self, keys, keep_reverse: bool = True):
        """keys: numeric array (vectorized) or sequence of objects."""
        h = key_identity64(keys)
        if keep_reverse:
            klist = keys.tolist() if isinstance(keys, np.ndarray) else keys
            with self._lock:
                for k, hv in zip(klist, h.tolist()):
                    self._rev.setdefault(hv, k)
        hi = (h >> np.uint64(32)).astype(np.uint32)
        lo = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        return hi, lo

    def rev_slice(self, start: int):
        """Atomic snapshot of the reverse map's append-only tail:
        ``(items[start:], len_at_snapshot)``. The checkpoint keymap log
        appends `items` and records the returned count — under the same
        lock encode inserts hold, so a concurrent prefetch-thread encode
        can never tear the iteration (dicts preserve insertion order, so
        the slice IS the keys seen since the last checkpoint)."""
        import itertools

        with self._lock:
            return (
                list(itertools.islice(self._rev.items(), start, None)),
                len(self._rev),
            )

    # kept as an alias for the columnar fast path's call sites
    encode_numeric = encode

    def decode(self, hi: np.ndarray, lo: np.ndarray):
        h = (np.asarray(hi, dtype=np.uint64) << np.uint64(32)) | np.asarray(
            lo, dtype=np.uint64
        )
        return [self._rev.get(int(v), int(v)) for v in h.tolist()]
