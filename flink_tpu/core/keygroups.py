"""Key groups: max-parallelism-stable hash sharding of keyed state.

Reproduces the *semantics* of the reference's key-group scheme
(flink-runtime/.../state/KeyGroupRangeAssignment.java:40-111 and
KeyGroupRange.java:30): a key is hashed, the hash is scrambled with murmur3 and
reduced modulo ``max_parallelism`` to a *key group*; key groups are assigned to
operator subtasks (here: mesh shards) in contiguous ranges. Rescaling a job
re-slices key-group ranges, never re-hashes keys.

Differences from the reference (deliberate, documented):
  * The reference hashes Java ``Object.hashCode()``; we hash a 64-bit key id
    (arbitrary host keys are first mapped to 64 bits by ``ops.hashing``).
  * All functions here have three flavors: Python scalar (tests/host control
    plane), numpy-vectorized (host batch prep), and jnp (on-device routing).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

DEFAULT_MAX_PARALLELISM = 128
UPPER_BOUND_MAX_PARALLELISM = 1 << 15

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)
_M5 = np.uint32(5)
_N1 = np.uint32(0xE6546B64)
_F1 = np.uint32(0x85EBCA6B)
_F2 = np.uint32(0xC2B2AE35)


def _rotl32(x, r: int, xp):
    x = x.astype(xp.uint32) if hasattr(x, "astype") else xp.uint32(x)
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def murmur3_32(code, xp=np):
    """murmur3 32-bit hash of a single 32-bit word (standard public algorithm,
    seed 0, length 4). Matches the scrambling role of the reference's
    MathUtils.murmurHash used by KeyGroupRangeAssignment.

    `code` may be a scalar or an array of uint32; `xp` is numpy or jax.numpy.
    Returns uint32.
    """
    if xp is np:
        with np.errstate(over="ignore"):
            return _murmur3_32_impl(code, xp)
    return _murmur3_32_impl(code, xp)


def _murmur3_32_impl(code, xp):
    k = xp.asarray(code).astype(xp.uint32)
    k = k * _C1
    k = _rotl32(k, 15, xp)
    k = k * _C2
    h = k  # seed 0: h = 0 ^ k
    h = _rotl32(h, 13, xp)
    h = h * _M5 + _N1
    h = h ^ xp.uint32(4)  # length in bytes
    h = h ^ (h >> xp.uint32(16))
    h = h * _F1
    h = h ^ (h >> xp.uint32(13))
    h = h * _F2
    h = h ^ (h >> xp.uint32(16))
    return h


def compute_key_group_for_key_hash(key_hash, max_parallelism: int, xp=np):
    """key hash (uint32) -> key group in [0, max_parallelism).

    Semantics of KeyGroupRangeAssignment.computeKeyGroupForKeyHash (ref :62):
    murmur-scramble then modulo.
    """
    return (murmur3_32(key_hash, xp) % xp.uint32(max_parallelism)).astype(xp.uint32)


def assign_to_key_group(key_hash, max_parallelism: int, xp=np):
    """Alias matching KeyGroupRangeAssignment.assignToKeyGroup (ref :51)."""
    return compute_key_group_for_key_hash(key_hash, max_parallelism, xp)


def compute_operator_index_for_key_group(
    max_parallelism: int, parallelism: int, key_group
):
    """key group -> operator (shard) index.

    Semantics of KeyGroupRangeAssignment.computeOperatorIndexForKeyGroup
    (ref :105): ``keyGroup * parallelism / maxParallelism`` in integer math,
    which yields contiguous, balanced ranges.
    Works on Python ints and numpy/jnp arrays (use int32-safe ranges:
    max_parallelism <= 2^15 so the product fits in int32).
    """
    return key_group * parallelism // max_parallelism


def key_group_range_for_operator(
    max_parallelism: int, parallelism: int, operator_index: int
) -> "KeyGroupRange":
    """Contiguous key-group range owned by one operator subtask.

    Semantics of KeyGroupRangeAssignment.computeKeyGroupRangeForOperatorIndex.
    """
    check_parallelism(max_parallelism, parallelism)
    start = (operator_index * max_parallelism + parallelism - 1) // parallelism
    end = ((operator_index + 1) * max_parallelism - 1) // parallelism
    return KeyGroupRange(start, end)


def check_parallelism(max_parallelism: int, parallelism: int) -> None:
    if not (0 < max_parallelism <= UPPER_BOUND_MAX_PARALLELISM):
        raise ValueError(
            f"max_parallelism must be in (0, {UPPER_BOUND_MAX_PARALLELISM}], "
            f"got {max_parallelism}"
        )
    if parallelism > max_parallelism:
        raise ValueError(
            f"parallelism {parallelism} exceeds max_parallelism {max_parallelism}"
        )


@dataclass(frozen=True)
class KeyGroupRange:
    """Inclusive range [start, end] of key groups (ref KeyGroupRange.java:30).

    An empty range is represented by start > end.
    """

    start: int
    end: int

    EMPTY: "KeyGroupRange" = None  # set below

    @property
    def num_key_groups(self) -> int:
        return 0 if self.start > self.end else self.end - self.start + 1

    def __contains__(self, key_group: int) -> bool:
        return self.start <= key_group <= self.end

    def __iter__(self) -> Iterator[int]:
        return iter(range(self.start, self.end + 1))

    def __len__(self) -> int:
        return self.num_key_groups

    def intersect(self, other: "KeyGroupRange") -> "KeyGroupRange":
        s, e = max(self.start, other.start), min(self.end, other.end)
        return KeyGroupRange(s, e) if s <= e else KeyGroupRange.EMPTY


object.__setattr__  # (keep linters quiet about frozen dataclass idiom)
KeyGroupRange.EMPTY = KeyGroupRange(0, -1)
