"""TypeInformation + extraction — the TypeExtractor analog (SURVEY §2.1,
ref flink-core api/common/typeinfo/TypeInformation.java +
api/java/typeutils/TypeExtractor.java).

The reference walks Java generics/POJO fields to build a TypeInformation
tree that picks serializers and comparators. The Python analog extracts
the same tree two ways:

  * ``of(sample)``   — from a runtime value (TypeExtractor.getForObject):
    scalars -> BasicTypeInfo, numpy arrays -> PrimitiveArrayTypeInfo,
    tuples -> TupleTypeInfo, NamedTuples/dataclasses -> RowTypeInfo (the
    PojoTypeInfo role: named, typed fields), dicts -> MapTypeInfo,
    lists -> ListTypeInfo, anything else -> GenericTypeInfo (the
    Kryo-fallback role, served by the registry's pickle fallback).
  * ``from_hint(tp)`` — from a typing annotation
    (TypeExtractor.createTypeInfo): ``int``, ``Tuple[int, str]``,
    ``List[float]``, ``Dict[str, int]``, ``Optional[T]``.

``create_serializer(registry)`` binds the tree to the job's
SerializerRegistry (TypeInformation.createSerializer), and flat numeric
rows expose ``to_schema()`` — the bridge onto the columnar RecordBatch
layout the device path consumes (core/types.Schema).
"""

from __future__ import annotations

import dataclasses
import typing
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import numpy as np

from flink_tpu.core.types import Field, Schema

_BASIC_DTYPES = {
    bool: np.dtype(bool),
    int: np.dtype(np.int64),
    float: np.dtype(np.float64),
    str: None,
    bytes: None,
}


class TypeInformation:
    """Base (ref TypeInformation.java): arity + serializer binding."""

    @property
    def arity(self) -> int:
        return 1

    def create_serializer(self, registry):
        """Default: the registry's envelope dispatch handles the value
        (TypeInformation.createSerializer)."""
        return registry

    def to_schema(self) -> Schema:
        raise TypeError(f"{self} has no flat columnar schema")

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash(repr(self))


@dataclass(frozen=True, eq=False)
class BasicTypeInfo(TypeInformation):
    """ref BasicTypeInfo: the primitive leaf types."""

    py_type: type

    def __repr__(self):
        return f"Basic<{self.py_type.__name__}>"

    @property
    def np_dtype(self):
        return _BASIC_DTYPES[self.py_type]


@dataclass(frozen=True, eq=False)
class PrimitiveArrayTypeInfo(TypeInformation):
    """ref PrimitiveArrayTypeInfo: fixed-dtype numpy arrays."""

    dtype: Any
    shape: Tuple[int, ...] = ()

    def __repr__(self):
        return f"Array<{np.dtype(self.dtype).name}{list(self.shape)}>"


@dataclass(frozen=True, eq=False)
class TupleTypeInfo(TypeInformation):
    """ref TupleTypeInfo: positional composite."""

    types: Tuple[TypeInformation, ...]

    @property
    def arity(self) -> int:
        return len(self.types)

    def __repr__(self):
        return f"Tuple<{', '.join(map(repr, self.types))}>"

    def to_schema(self) -> Schema:
        fields = []
        for i, t in enumerate(self.types):
            if not isinstance(t, BasicTypeInfo) or t.np_dtype is None:
                raise TypeError(
                    f"field {i} ({t!r}) is not a numeric scalar; no "
                    f"columnar schema"
                )
            fields.append(Field(f"f{i}", t.np_dtype))
        return Schema(tuple(fields))


@dataclass(frozen=True, eq=False)
class RowTypeInfo(TypeInformation):
    """ref RowTypeInfo / PojoTypeInfo: NAMED, typed fields (extracted
    from NamedTuples, dataclasses, or given explicitly)."""

    names: Tuple[str, ...]
    types: Tuple[TypeInformation, ...]

    @property
    def arity(self) -> int:
        return len(self.types)

    def __repr__(self):
        inner = ", ".join(f"{n}: {t!r}" for n, t in zip(self.names,
                                                        self.types))
        return f"Row<{inner}>"

    def to_schema(self) -> Schema:
        fields = []
        for n, t in zip(self.names, self.types):
            if isinstance(t, BasicTypeInfo) and t.np_dtype is not None:
                fields.append(Field(n, t.np_dtype))
            elif isinstance(t, PrimitiveArrayTypeInfo):
                fields.append(Field(n, np.dtype(t.dtype), t.shape))
            else:
                raise TypeError(
                    f"field {n!r} ({t!r}) is not columnar-layout eligible"
                )
        return Schema(tuple(fields))


@dataclass(frozen=True, eq=False)
class ListTypeInfo(TypeInformation):
    element: TypeInformation

    def __repr__(self):
        return f"List<{self.element!r}>"


@dataclass(frozen=True, eq=False)
class MapTypeInfo(TypeInformation):
    key: TypeInformation
    value: TypeInformation

    def __repr__(self):
        return f"Map<{self.key!r}, {self.value!r}>"


@dataclass(frozen=True, eq=False)
class GenericTypeInfo(TypeInformation):
    """ref GenericTypeInfo: the Kryo-fallback role — the registry's
    pickle fallback (or a user-registered serializer) handles it."""

    py_type: type

    def __repr__(self):
        return f"Generic<{self.py_type.__name__}>"


def of(value: Any) -> TypeInformation:
    """Extract from a sample value (ref TypeExtractor.getForObject)."""
    if isinstance(value, bool):
        return BasicTypeInfo(bool)
    if isinstance(value, int):
        return BasicTypeInfo(int)
    if isinstance(value, float):
        return BasicTypeInfo(float)
    if isinstance(value, str):
        return BasicTypeInfo(str)
    if isinstance(value, bytes):
        return BasicTypeInfo(bytes)
    if isinstance(value, np.generic):
        return PrimitiveArrayTypeInfo(value.dtype, ())
    if isinstance(value, np.ndarray):
        return PrimitiveArrayTypeInfo(value.dtype, tuple(value.shape))
    if isinstance(value, tuple):
        fields = getattr(value, "_fields", None)
        if fields is not None:          # NamedTuple -> named row
            return RowTypeInfo(tuple(fields),
                               tuple(of(v) for v in value))
        return TupleTypeInfo(tuple(of(v) for v in value))
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        fs = dataclasses.fields(value)
        return RowTypeInfo(
            tuple(f.name for f in fs),
            tuple(of(getattr(value, f.name)) for f in fs),
        )
    if isinstance(value, dict):
        if value:
            k, v = next(iter(value.items()))
            return MapTypeInfo(of(k), of(v))
        return MapTypeInfo(GenericTypeInfo(object), GenericTypeInfo(object))
    if isinstance(value, list):
        return ListTypeInfo(
            of(value[0]) if value else GenericTypeInfo(object)
        )
    return GenericTypeInfo(type(value))


def from_hint(tp) -> TypeInformation:
    """Extract from a typing annotation (ref TypeExtractor.createTypeInfo)."""
    if tp in _BASIC_DTYPES:
        return BasicTypeInfo(tp)
    origin = typing.get_origin(tp)
    args = typing.get_args(tp)
    if origin is tuple:
        if len(args) == 2 and args[1] is Ellipsis:
            return ListTypeInfo(from_hint(args[0]))
        return TupleTypeInfo(tuple(from_hint(a) for a in args))
    if origin is list:
        return ListTypeInfo(from_hint(args[0]) if args
                            else GenericTypeInfo(object))
    if origin is dict:
        if args:
            return MapTypeInfo(from_hint(args[0]), from_hint(args[1]))
        return MapTypeInfo(GenericTypeInfo(object), GenericTypeInfo(object))
    import types as _types

    if origin is typing.Union or origin is getattr(_types, "UnionType",
                                                   None):
        # Optional[T]: the reference treats nullable fields as T
        non_none = [a for a in args if a is not type(None)]
        if len(non_none) == 1:
            return from_hint(non_none[0])
        return GenericTypeInfo(object)
    if isinstance(tp, type):
        if issubclass(tp, tuple) and hasattr(tp, "_fields"):
            hints = typing.get_type_hints(tp)
            return RowTypeInfo(
                tuple(tp._fields),
                tuple(from_hint(hints.get(f, object))
                      if hints.get(f) is not None else GenericTypeInfo(object)
                      for f in tp._fields),
            )
        if dataclasses.is_dataclass(tp):
            hints = typing.get_type_hints(tp)
            fs = dataclasses.fields(tp)
            return RowTypeInfo(
                tuple(f.name for f in fs),
                tuple(from_hint(hints[f.name]) for f in fs),
            )
        if tp is np.ndarray:
            return PrimitiveArrayTypeInfo(np.float32, ())
        return GenericTypeInfo(tp)
    return GenericTypeInfo(object)
