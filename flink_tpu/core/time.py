"""Event time, processing time, watermarks.

Mirrors the contracts of the reference's TimeCharacteristic
(flink-streaming-java/.../api/TimeCharacteristic.java) and Watermark
(.../api/watermark/Watermark.java), TPU-adapted: timestamps on device are
int32 *ticks* relative to a per-job origin so everything stays in 32-bit
integer registers (TPU has no fast int64/f64 path). The host-side API speaks
int milliseconds; `TimeDomain` converts.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

# Sentinels (int32-representable; mirror Long.MIN_VALUE/MAX_VALUE roles)
MIN_TS = -(2**31) + 1
MAX_TS = 2**31 - 2
MAX_WATERMARK = MAX_TS  # end-of-stream watermark (ref Watermark.MAX_WATERMARK)


class TimeCharacteristic(enum.Enum):
    ProcessingTime = "processing-time"
    IngestionTime = "ingestion-time"
    EventTime = "event-time"


@dataclass(frozen=True)
class Watermark:
    """Event-time watermark: no elements with ts <= timestamp will follow."""

    timestamp: int

    def __le__(self, other):
        return self.timestamp <= other.timestamp


@dataclass(frozen=True)
class TimeDomain:
    """Mapping between host milliseconds and device int32 ticks.

    origin_ms: host epoch-ms mapped to tick 0.
    ms_per_tick: granularity (1 = millisecond ticks; covers ±24.8 days of
    event-time span per job at 1ms; raise for longer horizons).
    """

    origin_ms: int = 0
    ms_per_tick: int = 1

    def to_ticks(self, ms):
        t = (np.asarray(ms, dtype=np.int64) - self.origin_ms) // self.ms_per_tick
        return np.clip(t, MIN_TS, MAX_TS).astype(np.int32)

    def to_ms(self, ticks):
        return np.asarray(ticks, dtype=np.int64) * self.ms_per_tick + self.origin_ms


class Time:
    """Duration helpers (ref flink-streaming-java Time.java surface)."""

    @staticmethod
    def milliseconds(n: int) -> int:
        return int(n)

    @staticmethod
    def seconds(n: float) -> int:
        return int(n * 1000)

    @staticmethod
    def minutes(n: float) -> int:
        return int(n * 60_000)

    @staticmethod
    def hours(n: float) -> int:
        return int(n * 3_600_000)

    @staticmethod
    def days(n: float) -> int:
        return int(n * 86_400_000)
