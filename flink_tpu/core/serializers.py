"""Type serializer registry — the per-type serialization seam.

The reference routes every record and every state value through a
`TypeSerializer` chosen from `TypeInformation`, with user-registered
custom serializers layered on top (ref
flink-core/.../api/common/typeutils/TypeSerializer.java:39 and
ExecutionConfig.registerTypeWithKryoSerializer). Round 1 shipped arbitrary
Python objects through blanket pickle; this module restores the seam:

  * ``TypeSerializer`` — serialize/deserialize one value to/from bytes,
    plus a config-snapshot string used for restore-compatibility checks
    (the analog of TypeSerializerConfigSnapshot).
  * built-ins for the primitive lattice (long/double/bool/str/bytes),
    tuples, lists, dicts and numpy arrays — all self-describing and
    version-tagged.
  * ``PickleSerializer`` — the explicit fallback (the Kryo-analog), still
    available but now a *registered default* rather than the only path.
  * ``SerializerRegistry`` — type -> serializer mapping with a
    type-tagged envelope (``dumps_typed``/``loads_typed``) so
    heterogeneous state maps round-trip through registered serializers.

State snapshot/restore (state/backend.py) and checkpoint streams consult
the active registry; ``StateDescriptor(serializer=...)`` pins one state to
a specific serializer, mirroring descriptor-level serializer injection in
the reference (StateDescriptor.java:50).
"""

from __future__ import annotations

import io
import pickle
import struct
from typing import Any, Callable, Dict, Optional, Tuple, Type

import numpy as np


class SerializationError(RuntimeError):
    pass


class TypeSerializer:
    """One value <-> bytes. Subclasses must be stateless/reusable."""

    #: short stable identifier written into snapshots for compat checks
    uid: str = ""

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def config_snapshot(self) -> str:
        """Restore-compatibility token (TypeSerializerConfigSnapshot
        analog): restoring with a serializer whose snapshot differs is
        refused rather than silently misread."""
        return f"{type(self).__name__}:{self.uid}:v1"


class _StructSerializer(TypeSerializer):
    fmt = ""
    cast: Callable = None

    def serialize(self, value) -> bytes:
        return struct.pack(self.fmt, self.cast(value))

    def deserialize(self, data: bytes):
        return struct.unpack(self.fmt, data)[0]


class LongSerializer(_StructSerializer):
    uid = "long"
    fmt = "<q"
    cast = staticmethod(int)


class DoubleSerializer(_StructSerializer):
    uid = "double"
    fmt = "<d"
    cast = staticmethod(float)


class BoolSerializer(_StructSerializer):
    uid = "bool"
    fmt = "<?"
    cast = staticmethod(bool)


class StringSerializer(TypeSerializer):
    uid = "string"

    def serialize(self, value) -> bytes:
        return str(value).encode("utf-8")

    def deserialize(self, data: bytes):
        return data.decode("utf-8")


class BytesSerializer(TypeSerializer):
    uid = "bytes"

    def serialize(self, value) -> bytes:
        return bytes(value)

    def deserialize(self, data: bytes):
        return data


class NumpySerializer(TypeSerializer):
    """Arrays via the npy wire format (self-describing dtype + shape)."""

    uid = "ndarray"

    def serialize(self, value) -> bytes:
        buf = io.BytesIO()
        np.save(buf, np.asarray(value), allow_pickle=False)
        return buf.getvalue()

    def deserialize(self, data: bytes):
        return np.load(io.BytesIO(data), allow_pickle=False)


class PickleSerializer(TypeSerializer):
    """The explicit generic fallback (Kryo-analog)."""

    uid = "pickle"

    def serialize(self, value) -> bytes:
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, data: bytes):
        return pickle.loads(data)


class TupleSerializer(TypeSerializer):
    """Field-wise composite over a registry (TupleSerializer analog).

    Self-describing: each field rides the registry's typed envelope, so
    heterogeneous tuples round-trip without a schema."""

    uid = "tuple"

    def __init__(self, registry: "SerializerRegistry"):
        self._reg = registry

    def serialize(self, value) -> bytes:
        out = [struct.pack("<I", len(value))]
        for f in value:
            blob = self._reg.dumps_typed(f)
            out.append(struct.pack("<I", len(blob)))
            out.append(blob)
        return b"".join(out)

    def deserialize(self, data: bytes):
        (n,) = struct.unpack_from("<I", data, 0)
        off = 4
        fields = []
        for _ in range(n):
            (ln,) = struct.unpack_from("<I", data, off)
            off += 4
            fields.append(self._reg.loads_typed(data[off:off + ln]))
            off += ln
        return tuple(fields)


class ListSerializer(TupleSerializer):
    uid = "list"

    def deserialize(self, data: bytes):
        return list(super().deserialize(data))


class DictSerializer(TypeSerializer):
    uid = "dict"

    def __init__(self, registry: "SerializerRegistry"):
        self._reg = registry

    def serialize(self, value) -> bytes:
        items = list(value.items())
        out = [struct.pack("<I", len(items))]
        for k, v in items:
            for blob in (self._reg.dumps_typed(k), self._reg.dumps_typed(v)):
                out.append(struct.pack("<I", len(blob)))
                out.append(blob)
        return b"".join(out)

    def deserialize(self, data: bytes):
        (n,) = struct.unpack_from("<I", data, 0)
        off = 4
        out = {}
        for _ in range(n):
            kv = []
            for _ in range(2):
                (ln,) = struct.unpack_from("<I", data, off)
                off += 4
                kv.append(self._reg.loads_typed(data[off:off + ln]))
                off += ln
            out[kv[0]] = kv[1]
        return out


class SerializerRegistry:
    """type -> TypeSerializer with a type-tagged byte envelope.

    Envelope: uid '\\0' payload. Registered uids resolve to their
    serializer on read; unknown uids are a hard error (never silently
    pickled), so a snapshot written with a custom serializer demands the
    same registration to restore — the reference's restore-compat stance.
    """

    def __init__(self, copy_from: Optional["SerializerRegistry"] = None):
        self._by_type: Dict[type, TypeSerializer] = {}
        self._by_uid: Dict[str, TypeSerializer] = {}
        self._builtin_types: set = set()
        self._fallback = PickleSerializer()
        for t, s in (
            (bool, BoolSerializer()),      # before int: bool is an int
            (int, LongSerializer()),
            (float, DoubleSerializer()),
            (str, StringSerializer()),
            (bytes, BytesSerializer()),
            (np.ndarray, NumpySerializer()),
        ):
            self.register(t, s)
        self.register(tuple, TupleSerializer(self))
        self.register(list, ListSerializer(self))
        self.register(dict, DictSerializer(self))
        self._builtin_types = set(self._by_type)
        self._register_uid(self._fallback)
        if copy_from is not None:
            # fork: carry over the source's user registrations so a
            # job-scoped registry extends (never shadows) the process one
            for t, s in copy_from._by_type.items():
                if t not in copy_from._builtin_types:
                    self.register(t, s)

    # -- registration (ExecutionConfig.registerTypeWithKryoSerializer) ---
    def register(self, py_type: type, serializer: TypeSerializer):
        if not serializer.uid:
            raise ValueError("serializer needs a stable non-empty uid")
        self._by_type[py_type] = serializer
        self._register_uid(serializer)
        return serializer

    def _register_uid(self, serializer: TypeSerializer):
        prev = self._by_uid.get(serializer.uid)
        if prev is not None and type(prev) is not type(serializer):
            raise ValueError(
                f"uid {serializer.uid!r} already bound to {type(prev).__name__}"
            )
        self._by_uid[serializer.uid] = serializer

    def serializer_for(self, value) -> TypeSerializer:
        s = self._by_type.get(type(value))
        if s is not None:
            return s
        # Subclass walk over USER registrations only. Builtin container/
        # primitive serializers must not catch subclasses: a namedtuple or
        # IntEnum riding TupleSerializer/LongSerializer would silently
        # come back as a bare tuple/int after restore — those fall back to
        # pickle, which preserves the type.
        for t, s in self._by_type.items():
            if t not in self._builtin_types and isinstance(value, t):
                return s
        return self._fallback

    def by_uid(self, uid: str) -> TypeSerializer:
        s = self._by_uid.get(uid)
        if s is None:
            raise SerializationError(
                f"no serializer registered for uid {uid!r}; register the "
                f"custom serializer used to write this snapshot"
            )
        return s

    # -- typed envelope ---------------------------------------------------
    def dumps_typed(self, value) -> bytes:
        s = self.serializer_for(value)
        try:
            blob = s.serialize(value)
        except SerializationError:
            raise
        except (struct.error, OverflowError, ValueError) as e:
            # value outside the builtin wire format's range (int > int64,
            # object-dtype ndarray, ...): ride the generic fallback rather
            # than failing the snapshot. User-registered serializers do NOT
            # get this safety net — their failures are real errors, wrapped
            # as SerializationError so an enclosing builtin container
            # cannot swallow them into its own fallback.
            if s is not self._fallback and type(s) not in _BUILTIN_SER_TYPES:
                raise SerializationError(
                    f"serializer {s.uid!r} failed for "
                    f"{type(value).__name__}: {e}"
                ) from e
            s = self._fallback
            blob = s.serialize(value)
        return s.uid.encode("ascii") + b"\0" + blob

    def loads_typed(self, blob: bytes):
        sep = blob.find(b"\0")
        if sep < 0:
            raise SerializationError(
                "corrupt typed envelope: no uid separator in "
                f"{blob[:32]!r}{'...' if len(blob) > 32 else ''} "
                f"({len(blob)} bytes)"
            )
        return self.by_uid(blob[:sep].decode("ascii")).deserialize(
            blob[sep + 1:]
        )


#: builtin serializer classes eligible for the fallback safety net in
#: dumps_typed (user serializers fail loudly instead)
_BUILTIN_SER_TYPES = frozenset({
    BoolSerializer, LongSerializer, DoubleSerializer, StringSerializer,
    BytesSerializer, NumpySerializer, TupleSerializer, ListSerializer,
    DictSerializer,
})

#: process-wide default; jobs may carry their own via the environment
DEFAULT_REGISTRY = SerializerRegistry()
