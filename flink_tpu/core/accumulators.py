"""Accumulators — the flink-core accumulator API (SURVEY §2.1,
ref org.apache.flink.api.common.accumulators: Accumulator, IntCounter,
DoubleCounter, LongCounter, AverageAccumulator, Histogram).

User functions add values during execution; the job result exposes the
merged totals (`JobHandle.accumulator_results` / the DataSet
environment's last-job map). Single-controller runtime: merge across
subtasks collapses to merging per-operator instances.
"""

from __future__ import annotations

from typing import Any, Dict


class Accumulator:
    def add(self, value):
        raise NotImplementedError

    def get_local_value(self):
        raise NotImplementedError

    def merge(self, other: "Accumulator"):
        raise NotImplementedError

    def reset_local(self):
        raise NotImplementedError


class IntCounter(Accumulator):
    def __init__(self):
        self.value = 0

    def add(self, value=1):
        self.value += int(value)

    def get_local_value(self):
        return self.value

    def merge(self, other):
        self.value += other.get_local_value()

    def reset_local(self):
        self.value = 0


class LongCounter(IntCounter):
    pass


class DoubleCounter(Accumulator):
    def __init__(self):
        self.value = 0.0

    def add(self, value):
        self.value += float(value)

    def get_local_value(self):
        return self.value

    def merge(self, other):
        self.value += other.get_local_value()

    def reset_local(self):
        self.value = 0.0


class AverageAccumulator(Accumulator):
    def __init__(self):
        self.total = 0.0
        self.count = 0

    def add(self, value):
        self.total += float(value)
        self.count += 1

    def get_local_value(self):
        return self.total / self.count if self.count else 0.0

    def merge(self, other):
        self.total += other.total
        self.count += other.count

    def reset_local(self):
        self.total = 0.0
        self.count = 0


class Histogram(Accumulator):
    """Integer-bucket histogram (ref accumulators.Histogram: TreeMap of
    value -> count)."""

    def __init__(self):
        self.counts: Dict[int, int] = {}

    def add(self, value):
        v = int(value)
        self.counts[v] = self.counts.get(v, 0) + 1

    def get_local_value(self):
        return dict(sorted(self.counts.items()))

    def merge(self, other):
        for v, c in other.counts.items():
            self.counts[v] = self.counts.get(v, 0) + c

    def reset_local(self):
        self.counts.clear()


class AccumulatorRegistry:
    """Per-job registry (ref StreamingRuntimeContext.addAccumulator /
    getAccumulator + JobExecutionResult.getAccumulatorResult)."""

    def __init__(self):
        self._acc: Dict[str, Accumulator] = {}

    def add(self, name: str, accumulator: Accumulator):
        cur = self._acc.get(name)
        if cur is not None and cur is not accumulator:
            raise ValueError(f"accumulator {name!r} already registered")
        self._acc[name] = accumulator

    def get(self, name: str) -> Accumulator:
        return self._acc[name]

    def results(self) -> Dict[str, Any]:
        return {n: a.get_local_value() for n, a in self._acc.items()}

    # -- checkpoint integration (the reference discards a failed
    # attempt's accumulator values; here values roll back to the
    # checkpoint cut so recovery neither loses nor double-counts) -------
    def snapshot(self) -> Dict[str, Accumulator]:
        import copy

        return {n: copy.deepcopy(a) for n, a in self._acc.items()}

    def restore(self, snap: Dict[str, Accumulator]):
        """In-place rollback: user functions hold live references to
        their accumulator objects, so values are reset and re-merged
        rather than replaced."""
        for n, a in self._acc.items():
            a.reset_local()
            saved = snap.get(n)
            if saved is not None:
                a.merge(saved)
        for n, saved in snap.items():       # registered pre-crash only
            self._acc.setdefault(n, saved)
