"""Elastic survival: device/shard-loss classification and survivor
planning (ISSUE 8; the availability half of the multi-chip story).

At fleet scale a chip dying is background noise, and the job's duty is
to keep serving from the survivors rather than crash-loop at a
parallelism its mesh no longer has (the Hazelcast Jet argument:
availability at the tail is the product). The key-group scheme was
designed for exactly this — rescale re-slices contiguous key-group
ranges, never re-hashes keys (core/keygroups.py), and the logical
snapshot format restores at ANY parallelism (runtime/checkpoint.py) —
so shard loss is a *re-plan*, not a death:

    classify the failure as device loss  (this module)
      -> re-slice key-group ranges over the M surviving shards
      -> rebuild MeshContext + the jitted step family at n_shards=M
      -> rescaled restore from the last durable cut
      -> resume exactly-once in DEGRADED mode

and the reverse edge — a triggered scale-back-up once replacement
capacity exists — bounds the degradation. The executor owns the
re-plan (runtime/executor.py `_recover`); this module owns what can be
decided *without* the executor's closures: what counts as device loss,
which devices survive, and the thread-safe degraded-state ledger the
web route and the elasticity drill read.

Failure classification (docs/fault-tolerance.md):

* :class:`DeviceLostError` — raised directly (the ``device_loss``
  fault class in testing/faults.py injects it at the ``step.dispatch``
  point), or detected by :func:`as_device_loss` from the runtime
  errors a dying chip actually produces: an ``XlaRuntimeError`` whose
  message carries a device-loss marker, a watchdog trip in a
  device-wait phase whose health probe finds a dead device, or DCN
  peer loss after reconnect exhaustion (that host's mesh segment is
  gone — runtime/dcn.py raises a :class:`DeviceLostError` subclass).
* Device loss is NEVER "transient" (no warm restart: the live state
  straddles a dead device) and never "state-corrupting" in the usual
  sense (the checkpoint is fine; the *mesh* is wrong) — it is its own
  recovery kind.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence

import jax
import numpy as np


class DeviceLostError(RuntimeError):
    """A mesh shard's device is gone (chip failure, host segment loss).

    ``lost_shards``: indices into the CURRENT mesh's shard axis;
    ``lost_devices``: jax device objects, for callers that identify the
    casualty directly (the health probe). Either may be empty — a loss
    without an attributable shard still classifies as device loss, and
    recovery falls back to a full restore at the current parallelism.
    """

    def __init__(self, message: str, lost_shards: Sequence[int] = (),
                 lost_devices: Sequence = ()):
        super().__init__(message)
        self.lost_shards = tuple(int(s) for s in lost_shards)
        self.lost_devices = tuple(lost_devices)


class ElasticCapacityError(RuntimeError):
    """Survivors fell below ``recovery.min-shards``: degraded operation
    is no longer acceptable, so the job FAILS instead of re-planning.
    Deliberately not retried by the recovery loop — retrying cannot
    grow the surviving device set."""


# lowercase substrings of the runtime errors a lost accelerator
# actually surfaces (XLA/PJRT wording varies by backend + version, so
# this is a marker list, not a parse; the health probe is the
# confirming signal where one can run)
DEVICE_LOSS_MARKERS = (
    "device_lost",
    "device lost",
    "device is lost",
    "device failure",
    "device unavailable",
    "device or resource busy",
    "chip is unhealthy",
    "failed to enqueue",
    "halted",
)

# watchdog phases that wait ON the device: a deadline trip there with a
# failing health probe is a dead chip, not a slow one. (These are the
# armed phase names from runtime/executor.py — the dispatch itself is
# not watchdog-armed; a chip dying mid-dispatch surfaces as a runtime
# error out of the dispatch call, the marker path above.)
_DEVICE_WAIT_PHASES = ("fire", "barrier_fetch", "restore")


def probe_devices(devices) -> List:
    """Health-probe each device with a trivial round-trip computation;
    returns the sublist that FAILED (dead/unreachable devices). Runs
    only on the recovery path — steady state never calls it."""
    dead = []
    for d in devices:
        try:
            x = jax.device_put(np.zeros((), np.int32), d)
            jax.block_until_ready(x + 1)  # host-sync-ok: recovery-path device health probe, never on the step loop
        except Exception:
            dead.append(d)
    return dead


def as_device_loss(exc: BaseException,
                   devices=None) -> Optional[DeviceLostError]:
    """Classify ``exc`` as device loss, or return None.

    The three production surfaces, in order of confidence:

    1. A :class:`DeviceLostError` (or subclass — DCN peer loss after
       reconnect exhaustion) passes through as-is.
    2. An XLA/PJRT runtime error whose message matches a device-loss
       marker; the health probe over ``devices`` attributes the
       casualty (an unattributable marker match still classifies, and
       recovery falls back to a same-parallelism full restore).
    3. A watchdog trip in a device-wait phase whose health probe finds
       a dead device — a hang and a death look identical from the host
       until the probe separates them, so the probe is REQUIRED here
       (a trip with every device healthy stays a plain watchdog trip).
    """
    if isinstance(exc, DeviceLostError):
        return exc
    mod = type(exc).__module__ or ""
    txt = f"{type(exc).__name__}: {exc}".lower()
    if ("jaxlib" in mod or "jax" in mod or
            type(exc).__name__ == "XlaRuntimeError"):
        if any(m in txt for m in DEVICE_LOSS_MARKERS):
            dead = probe_devices(devices) if devices else []
            return DeviceLostError(
                f"device loss detected from runtime error: {exc}",
                lost_devices=dead,
            )
    from flink_tpu.runtime.watchdog import WatchdogError

    if isinstance(exc, WatchdogError) and devices and \
            getattr(exc, "phase", "") in _DEVICE_WAIT_PHASES:
        dead = probe_devices(devices)
        if dead:
            return DeviceLostError(
                f"device loss detected behind watchdog trip "
                f"({exc.phase}): {len(dead)} device(s) failed the "
                f"health probe",
                lost_devices=dead,
            )
    return None


def plan_survivors(current_devices, loss: DeviceLostError):
    """(survivors, newly_lost) given the current mesh's device order and
    a classified loss. Shard indices resolve against ``current_devices``
    (the mesh axis order); device objects match by identity. Both lists
    preserve mesh order so the re-sliced key-group ranges stay
    contiguous over the survivors."""
    newly = []
    for s in loss.lost_shards:
        if 0 <= s < len(current_devices):
            d = current_devices[s]
            if d not in newly:
                newly.append(d)
    for d in loss.lost_devices:
        if d in current_devices and d not in newly:
            newly.append(d)
    survivors = [d for d in current_devices if d not in newly]
    return survivors, newly


class ElasticityController:
    """Thread-safe degraded-state ledger + scale-back request box for
    one windowed job.

    The executor records every re-plan (``record``); web threads read
    ``report`` (served at ``/jobs/<jid>/elasticity``); the operator —
    or the elasticity drill — calls :meth:`request_scale_up` once
    replacement capacity exists, and the step loop performs the
    savepoint-cut rescale at the next cycle boundary. Requests are a
    single latched flag: re-requesting before the loop serviced the
    first is idempotent."""

    def __init__(self, devices):
        self._lock = threading.Lock()
        # the job's FULL capacity: the mesh it was planned at. Scale-up
        # targets this set (in simulation the "lost" device is reusable;
        # on real hardware the operator requests scale-up only once the
        # replacement is registered under the same device ids).
        self.full_devices = list(devices)
        self.current_shards = len(self.full_devices)
        self.lost: List[str] = []       # device str()s, newest last
        self.rescales: List[dict] = []  # bounded history, newest last
        self.total_rescales = 0
        self._scale_up = threading.Event()

    @property
    def full_shards(self) -> int:
        return len(self.full_devices)

    @property
    def degraded_shards(self) -> int:
        return max(0, self.full_shards - self.current_shards)

    @property
    def degraded(self) -> bool:
        return self.degraded_shards > 0

    # -- executor side ---------------------------------------------------
    def record(self, kind: str, from_shards: int, to_shards: int,
               cause: str = "", lost=(), mttr_ms: Optional[float] = None):
        """One completed re-plan: kind 'degrade' (shard loss) or
        'scale_up' (capacity restored)."""
        with self._lock:
            self.current_shards = int(to_shards)
            if kind == "scale_up":
                self.lost = []
            else:
                self.lost.extend(str(d) for d in lost)
            self.total_rescales += 1
            self.rescales.append({
                "kind": kind,
                "from_shards": int(from_shards),
                "to_shards": int(to_shards),
                "cause": cause[:300],
                "lost": [str(d) for d in lost],
                "mttr_ms": (
                    round(mttr_ms, 2) if mttr_ms is not None else None
                ),
                "t_wall": round(time.time(), 3),
                "t_perf": time.perf_counter(),
            })
            del self.rescales[:-50]

    # -- operator side ---------------------------------------------------
    def request_scale_up(self):
        """Ask the job to rescale back to full capacity at the next
        cycle boundary (a savepoint-cut live rescale — exactly-once,
        no restart)."""
        self._scale_up.set()

    def take_scale_up_request(self) -> bool:
        """Step-loop poll: True exactly once per latched request."""
        if self._scale_up.is_set():
            self._scale_up.clear()
            return True
        return False

    # -- observability ---------------------------------------------------
    def report(self) -> dict:
        with self._lock:
            return {
                "full-shards": self.full_shards,
                "current-shards": self.current_shards,
                "degraded": self.degraded,
                "degraded-shards": self.degraded_shards,
                "lost-devices": list(self.lost),
                "rescales": [
                    {k: v for k, v in r.items() if k != "t_perf"}
                    for r in self.rescales
                ],
                "total-rescales": self.total_rescales,
                "scale-up-pending": self._scale_up.is_set(),
            }
