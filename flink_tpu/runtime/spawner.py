"""Long-lived fork thread with the abandoned-request claim protocol.

Two independent constraints force every process fork through ONE
long-lived thread:

  * ``PR_SET_PDEATHSIG`` fires when the forking THREAD dies, not the
    process — forking from a short-lived request-handler thread would
    SIGKILL the child the moment that thread exits
    (``process_cluster._die_with_parent``).
  * a requester that times out must either prevent the fork or
    guarantee the forked process does not outlive the abandonment
    untracked — otherwise a job/container runs with no record owning
    it.

The claim protocol (two GIL-atomic ``setdefault`` points) resolves the
requester/spawner race in both windows: before the fork ("owner") and
after it ("result"). Extracted from ``ProcessCluster`` so its one
subtle concurrency dance has exactly one implementation; the YARN
MiniYarnRM NodeManager role reuses it for container launches.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Optional


class AbandonableSpawner:
    """Runs fork callables on one long-lived thread; abandoned results
    are destroyed via the request's ``on_abandon`` callback."""

    def __init__(self, name: str = "spawner"):
        self._q: queue.Queue = queue.Queue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=name
        )
        self._thread.start()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, on_abandon, box, ev = item
            # GIL-atomic claim: a caller that timed out owns the box and
            # the request must NOT fork (an abandoned child would run
            # untracked)
            if box.setdefault("owner", "spawner") != "spawner":
                ev.set()
                continue
            try:
                res = fn()
                # second claim point: a caller that timed out AFTER the
                # fork owns "result" — its child must not outlive the
                # abandonment untracked
                if box.setdefault("result", "delivered") == "abandoned":
                    if on_abandon is not None:
                        on_abandon(res)
                else:
                    box["res"] = res
            except Exception as e:   # surfaced to the requesting thread
                box["err"] = e
            ev.set()

    def submit(self, fn: Callable[[], Any],
               on_abandon: Optional[Callable[[Any], None]] = None,
               timeout_s: float = 60.0) -> Any:
        """Run ``fn`` on the spawner thread; return its result or raise
        its exception. On timeout the request is abandoned: either the
        fork never happens, or ``on_abandon(result)`` destroys it."""
        box: dict = {}
        ev = threading.Event()
        self._q.put((fn, on_abandon, box, ev))
        if not ev.wait(timeout_s):
            if box.setdefault("owner", "caller") == "caller":
                raise TimeoutError("spawner thread unresponsive")
            ev.wait(timeout_s)   # spawner claimed it concurrently
        if "err" in box:
            raise box["err"]
        # presence-keyed, not value-keyed: fn may legitimately return None
        if "res" not in box:
            if box.setdefault("result", "abandoned") == "abandoned":
                # the spawner destroys the result if the fork ever lands
                raise TimeoutError("fork did not complete in time")
            # spawner claimed delivery first: it stores res then sets ev
            ev.wait(5)
            if "res" not in box:
                raise TimeoutError("spawn result lost")
        return box["res"]

    def stop(self):
        self._q.put(None)
