"""Checkpointing: consistent snapshots, restore, and key-group rescaling.

The reference implements Chandy-Lamport asynchronous barrier snapshotting
(CheckpointCoordinator triggering barriers through the dataflow,
BarrierBuffer alignment, per-key-group state files — SURVEY §3.4). In the
micro-batch SPMD design the barrier is structural: BETWEEN two steps, device
state + source offsets form a consistent cut, so a checkpoint is simply

    device state  --DMA-->  host  -->  logical entry format  -->  files

**Logical snapshot format** (the savepoint philosophy, ref SavepointV1 +
KeyGroupsStateHandle): state is stored as (key, pane, value) entries plus
scalars, independent of the physical hash-slot layout. Restoring at a
different parallelism re-buckets entries by key group onto the new mesh —
the analog of StateAssignmentOperation redistributing KeyGroupsStateHandles,
validated the way RescalingITCase does.

Exactly-once applies to STATE: sources snapshot offsets at the same cut, so
replay after restore reproduces identical micro-batches and state converges
to the no-failure result. Sinks see at-least-once on recovery (fires between
the checkpoint and the failure are re-emitted), like the reference without
transactional sinks; idempotent sinks recover exactly-once end-to-end.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
import uuid
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.ops import hashtable
from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import route_hash
from flink_tpu.testing import faults

# v2: numeric key identities are raw 64-bit key bits (hashing.
# key_identity64), not splitmix64 hashes — v1 snapshots' khi/klo would
# silently mismatch records encoded under the new identity, so the
# version gate turns that into a clean format error instead
FORMAT_VERSION = 2


@dataclass
class SnapshotMeta:
    checkpoint_id: int
    timestamp: float
    watermark: int
    fired_through: int
    max_pane: int
    min_pane: int
    dropped_late: int
    dropped_capacity: int
    source_offsets: object
    aux: dict


def stage_window_state(state: wk.WindowShardState, rows=None,
                       red: wk.ReduceSpec = None) -> dict:
    """SYNC phase of a snapshot: device -> host staging buffer.

    Fetches the bulk per-shard arrays of the selected shard ``rows``
    (incremental checkpoints pass only the shards owning dirty key
    groups; None = all) plus the tiny global scalars, in one batched
    device_get. Everything returned is a host numpy COPY, so the caller
    can hand the staging buffer to the background materializer and keep
    donating the live device buffers to subsequent steps.

    PACKED-plane state (``state.packed >= 0``) unpacks into the split
    (acc, touched) staging form here, so the logical snapshot format —
    and therefore restore compatibility across plane layouts — is
    independent of how the live device planes were stored. ``red`` is
    required then (the touch column derives through its neutral).
    """
    S = int(state.acc.shape[0])
    packed = state.packed >= 0
    if packed and red is None:
        raise ValueError("staging packed-plane state requires the "
                         "stage's ReduceSpec")
    all_rows = rows is None or len(rows) == S
    rows = list(range(S)) if rows is None else sorted(int(r) for r in rows)
    if all_rows:
        bulk = {
            "keys": state.table.keys, "acc": state.acc,
            "pane_ids": state.pane_ids, "fresh": state.fresh,
        }
        if not packed:
            bulk["touched"] = state.touched
    else:
        # lazy row slices: only the dirty shards' bytes cross the link
        bulk = {
            "keys": [state.table.keys[s] for s in rows],
            "acc": [state.acc[s] for s in rows],
            "pane_ids": [state.pane_ids[s] for s in rows],
            "fresh": [state.fresh[s] for s in rows],
        }
        if not packed:
            bulk["touched"] = [state.touched[s] for s in rows]
    small = {
        "watermark": state.watermark, "fired_through": state.fired_through,
        "max_pane": state.max_pane, "min_pane": state.min_pane,
        "dropped_late": state.dropped_late,
        "dropped_capacity": state.dropped_capacity,
    }
    bulk_h, small_h = jax.device_get((bulk, small))
    shards = {}
    for i, s in enumerate(rows):
        sh = {
            k: np.asarray(bulk_h[k][s if all_rows else i])
            for k in bulk_h
        }
        if packed:
            sh["acc"], sh["touched"] = wk.split_packed(
                sh["acc"], state.packed, red
            )
            sh["acc"] = np.ascontiguousarray(sh["acc"])
            sh["touched"] = np.asarray(sh["touched"])
        shards[s] = sh
    # value tail shape/dtype from the LIVE acc ([S, C*R, *tail]): an
    # empty staging (zero dirty shards) must still write correctly-
    # shaped empty entry arrays for vector / non-f32 reductions
    if packed:
        value_tail = () if state.packed == 0 else (state.acc.shape[-1] - 1,)
    else:
        value_tail = tuple(state.acc.shape[2:])
    value_dtype = np.dtype(state.acc.dtype)
    scalars = {
        "watermark": int(np.asarray(small_h["watermark"]).min()),
        "fired_through": int(np.asarray(small_h["fired_through"]).min()),
        "max_pane": int(np.asarray(small_h["max_pane"]).max()),
        "min_pane": int(np.asarray(small_h["min_pane"]).min()),
        "dropped_late": int(np.asarray(small_h["dropped_late"]).sum()),
        "dropped_capacity": int(
            np.asarray(small_h["dropped_capacity"]).sum()
        ),
    }
    return {"n_shards": S, "rows": rows, "shards": shards,
            "scalars": scalars, "value_tail": value_tail,
            "value_dtype": value_dtype}


def extract_entries(staged: dict, win: wk.WindowSpec):
    """ASYNC phase: staging buffer -> logical (key, pane, value) entries.

    Pure host numpy over the staged copies — runs on the materializer
    thread without touching device state. Returns (entries, scalars)."""
    R = win.ring
    khi_l, klo_l, pane_l, val_l, fresh_l = [], [], [], [], []
    for s in staged["rows"]:
        sh = staged["shards"][s]
        keys = sh["keys"]                       # [C, 2]
        acc = sh["acc"]                         # [C*R, ...]
        C = keys.shape[0]
        t2 = sh["touched"].reshape(R, C)   # ring-major device layout
        rings, slots = np.nonzero(t2)
        if slots.size == 0:
            continue
        khi_l.append(keys[slots, 0])
        klo_l.append(keys[slots, 1])
        pane_l.append(sh["pane_ids"][rings])
        val_l.append(acc.reshape((R, C) + acc.shape[1:])[rings, slots])
        fresh_l.append(sh["fresh"].reshape(R, C)[rings, slots])
    if khi_l:
        entries = {
            "key_hi": np.concatenate(khi_l),
            "key_lo": np.concatenate(klo_l),
            "pane": np.concatenate(pane_l).astype(np.int32),
            "value": np.concatenate(val_l),
            "fresh": np.concatenate(fresh_l),
        }
    else:
        entries = {
            "key_hi": np.zeros(0, np.uint32),
            "key_lo": np.zeros(0, np.uint32),
            "pane": np.zeros(0, np.int32),
            "value": np.zeros(
                (0,) + tuple(staged["value_tail"]), staged["value_dtype"]
            ),
            "fresh": np.zeros(0, bool),
        }
    return entries, dict(staged["scalars"])


def snapshot_window_state(state: wk.WindowShardState, win: wk.WindowSpec,
                          red: wk.ReduceSpec = None):
    """Device -> logical entries. state is the stacked [n_shards, ...]
    tree. The synchronous composition of stage + extract — the sync-full
    path and savepoints use it directly. ``red`` is required for
    packed-plane state (see stage_window_state)."""
    return extract_entries(stage_window_state(state, red=red), win)


def restore_window_rows(entries, scalars, ctx, spec, rows=None,
                        leftover=None) -> dict:
    """Host half of a restore: logical entries -> per-shard host arrays
    for the given shard ``rows`` (None = all shards, the full-restore
    path). The warm in-process restart passes only the shards whose
    key-group range went dirty since the restored cut, so the host
    rebuild and the device re-stage scale with what diverged instead of
    with what exists. Returns stacked ``[len(rows), ...]`` numpy arrays:
    ``{"keys", "acc", "touched", "fresh", "pane_ids", "n_fresh"}``."""
    R = spec.win.ring
    C = spec.capacity_per_shard
    rows = list(range(ctx.n_shards)) if rows is None \
        else sorted(int(r) for r in rows)

    khi = entries["key_hi"]
    klo = entries["key_lo"]
    pane = entries["pane"]
    value = entries["value"]
    e_fresh = entries.get("fresh", np.zeros(len(pane), bool))

    max_pane = scalars["max_pane"]
    have = max_pane != int(wk.PANE_NONE)
    # drop entries that fell off the (possibly smaller) ring horizon
    if have and len(pane):
        keep = pane > max_pane - R
        khi, klo, pane, value, e_fresh = (
            khi[keep], klo[keep], pane[keep], value[keep], e_fresh[keep]
        )

    kg = assign_to_key_group(route_hash(khi, klo, np), ctx.max_parallelism, np)
    shard_tables = []
    shard_accs = []
    shard_touched = []
    shard_fresh = []
    pane_rows = []
    starts, ends = ctx.kg_bounds()
    direct = getattr(spec, "layout", "hash") == "direct"
    for s in rows:
        sel = (kg >= starts[s]) & (kg <= ends[s])
        e_hi, e_lo = khi[sel], klo[sel]
        e_pane, e_val = pane[sel], value[sel]
        e_fr = e_fresh[sel]

        # layout-specific half: build the table and resolve each entry to
        # its slot; entries that do not fit go to leftover (the caller's
        # spill tier) in either layout
        def _spill(lost):
            if leftover is None:
                raise RuntimeError(
                    "restore: state does not fit the configured capacity"
                )
            leftover.append((
                e_hi[lost], e_lo[lost], e_pane[lost], e_val[lost]
            ))

        if direct:
            # direct-index layout: slot == key (identity table, see
            # wk.init_state layout="direct")
            fit = (e_hi == 0) & (e_lo < C)
            if not bool(fit.all()):
                _spill(~fit)
                e_lo, e_pane, e_val, e_fr = (
                    e_lo[fit], e_pane[fit], e_val[fit], e_fr[fit]
                )
            entry_slots = e_lo.astype(np.int64)
            iota = np.arange(C, dtype=np.uint32)
            table_keys = np.stack([np.zeros_like(iota), iota], axis=1)
        elif len(e_hi):
            # unique keys (entries repeat per pane)
            u_keys, inv = np.unique(
                (e_hi.astype(np.uint64) << np.uint64(32)) | e_lo,
                return_inverse=True,
            )
            u_hi = (u_keys >> np.uint64(32)).astype(np.uint32)
            u_lo = (u_keys & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            table, slots, ok = hashtable.upsert(
                hashtable.create(C, spec.probe_len),
                jnp.asarray(u_hi), jnp.asarray(u_lo),
                jnp.ones(len(u_hi), dtype=bool),
            )
            ok = np.asarray(ok)
            if not bool(ok.all()):
                _spill(~ok[inv])         # per-entry mask of unfitted keys
                keep_e = ok[inv]
                e_pane, e_val, e_fr = (
                    e_pane[keep_e], e_val[keep_e], e_fr[keep_e]
                )
                inv = inv[keep_e]
            entry_slots = np.asarray(slots)[inv]
            table_keys = np.asarray(table.keys)
        else:
            entry_slots = np.zeros(0, np.int64)
            table_keys = np.asarray(hashtable.create(C, spec.probe_len).keys)

        # shared half: scatter entries into the ring-major pane arrays
        acc_s = np.asarray(
            jnp.broadcast_to(
                spec.red.neutral_value(), (C * R,) + spec.red.value_shape
            ).astype(spec.red.dtype)
        ).copy()
        touched_s = np.zeros(C * R, bool)
        fresh_s = np.zeros(C * R, bool)
        if len(entry_slots):
            flat = (e_pane % R) * C + entry_slots
            acc_s[flat] = e_val
            touched_s[flat] = True
            fresh_s[flat] = e_fr
        shard_tables.append(table_keys)
        shard_accs.append(acc_s)
        shard_touched.append(touched_s)
        shard_fresh.append(fresh_s)
        if have:
            r_idx = np.arange(R)
            p_r = max_pane - ((max_pane - r_idx) % R)
            pane_rows.append(p_r.astype(np.int32))
        else:
            pane_rows.append(np.full(R, int(wk.PANE_NONE), np.int32))
    return {
        "keys": np.stack(shard_tables),
        "acc": np.stack(shard_accs),
        "touched": np.stack(shard_touched),
        "fresh": np.stack(shard_fresh),
        "pane_ids": np.stack(pane_rows),
        "n_fresh": np.asarray(
            [int(f.sum()) for f in shard_fresh], np.int32
        ),
    }


def restore_window_state(entries, scalars, ctx, spec, leftover=None):
    """Logical entries -> device state on a (possibly different) mesh.

    Re-buckets every entry by key group onto ctx's shard ranges, re-inserts
    keys into fresh hash tables, scatters pane values. The ring is
    re-registered from the global max_pane.

    leftover: optional list — entries whose key does not fit the table
    (snapshot taken with a spill tier, restored into a smaller/equal
    capacity) are appended as (key_hi, key_lo, pane, value) arrays for the
    caller to route back into its spill tier; without the list the
    overrun raises.
    """
    built = restore_window_rows(entries, scalars, ctx, spec,
                                leftover=leftover)

    def stack_put(a, dtype=None):
        a = np.stack(a) if isinstance(a, list) else a
        return jax.device_put(
            a if dtype is None else a.astype(dtype), ctx.state_sharding
        )

    S = ctx.n_shards
    # snapshot entries are logical, so a checkpoint restores into EITHER
    # plane layout: packed stages re-pack the split host arrays here
    packed = bool(getattr(spec, "packed", False))
    if packed:
        acc_dev = stack_put(
            wk.make_packed(built["acc"], built["touched"], spec.red)
        )
        touched_dev = stack_put(np.zeros((S, 0), bool))
    else:
        acc_dev = stack_put(built["acc"])
        touched_dev = stack_put(built["touched"])
    new_state = wk.WindowShardState(
        table=hashtable.SlotTable(stack_put(built["keys"]), spec.probe_len),
        acc=acc_dev,
        touched=touched_dev,
        pane_ids=stack_put(built["pane_ids"]),
        max_pane=_scal(S, scalars["max_pane"], ctx),
        min_pane=_scal(S, scalars["min_pane"], ctx),
        watermark=_scal(S, scalars["watermark"], ctx),
        fired_through=_scal(S, scalars["fired_through"], ctx),
        purged_through=_scal(
            S,
            scalars["fired_through"] - (spec.win.panes_per_window - 1)
            if scalars["fired_through"] != int(wk.PANE_NONE)
            else int(wk.PANE_NONE),
            ctx,
        ),
        dropped_late=_scal(S, scalars["dropped_late"], ctx, split=True),
        dropped_capacity=_scal(S, scalars["dropped_capacity"], ctx, split=True),
        fresh=stack_put(built["fresh"]),
        n_fresh=jax.device_put(built["n_fresh"], ctx.state_sharding),
        # overflow ring restores empty: a checkpoint is taken at a fire
        # boundary where the ring was drained into the spill tier, and the
        # spill entries ride the snapshot as regular logical entries
        ovf_hi=stack_put([np.zeros(spec.win.overflow, np.uint32)] * S),
        ovf_lo=stack_put([np.zeros(spec.win.overflow, np.uint32)] * S),
        ovf_pane=stack_put(
            [np.full(spec.win.overflow, int(wk.PANE_NONE), np.int32)] * S
        ),
        ovf_val=stack_put(
            [np.zeros((spec.win.overflow,) + spec.red.value_shape,
                      np.asarray(jnp.zeros((), spec.red.dtype)).dtype)] * S
        ),
        ovf_n=_scal(S, 0, ctx, split=True),
        # changelog restarts clean: the restored state IS the chain's
        # state, so the next incremental checkpoint extends that chain
        kg_dirty=stack_put([np.zeros(ctx.max_parallelism, bool)] * S),
        packed=len(spec.red.value_shape) if packed else -1,
    )
    return new_state


def _scal(S, v, ctx, split=False):
    if split:
        # counters: keep the global total on shard 0 so sums stay correct
        arr = np.zeros(S, np.int32)
        arr[0] = v
    else:
        arr = np.full(S, v, np.int32)
    return jax.device_put(arr, ctx.state_sharding)


class CheckpointStorage:
    """Directory layout:  <dir>/chk-<id>/{meta.json, entries.npz, aux.pkl
    [, manifest.json]}  (ref FsStateBackend checkpoint stream role).

    Incremental checkpoints add a manifest.json (checkpointing/manifest)
    naming the chain of checkpoint ids they depend on; retention GC keeps
    every directory a retained manifest references, so a delta can never
    outlive its base.

    ``local``: optional task-local snapshot cache (checkpointing/local.py,
    ref Flink task-local recovery). Every publish mirrors into it and
    every read prefers the verified local copy per checkpoint directory
    (i.e. per chain member for delta restores), falling back to primary
    on miss/corruption; its retention follows this storage's chain-
    closure GC so the tiers never disagree about the restorable cut."""

    def __init__(self, directory: str, retain: int = 2, local=None):
        self.dir = directory
        self.retain = retain
        self.local = local
        os.makedirs(directory, exist_ok=True)
        # per-incarnation identity token: wiping + re-creating the
        # checkpoint directory restarts cids at 1, so a surviving local
        # cache could otherwise serve the OLD job's chk-<cid> with
        # perfectly self-consistent CRCs. Best-effort (a read-only
        # primary runs without the staleness check, as before).
        self.storage_id = None
        id_path = os.path.join(directory, ".storage-id")
        try:
            if not os.path.exists(id_path):
                with open(id_path, "w") as f:
                    f.write(uuid.uuid4().hex)
            with open(id_path) as f:
                self.storage_id = f.read().strip() or None
        except OSError:
            pass
        if self.local is not None:
            self.local.bind_identity(self.storage_id)

    def path(self, cid: int) -> str:
        return os.path.join(self.dir, f"chk-{cid}")

    def write(self, cid: int, entries, scalars, source_offsets=None,
              aux: dict = None, manifest: dict = None, aux_bytes=None):
        """aux_bytes: pre-pickled {"source_offsets", "aux"} payload — the
        async path serializes it on the BARRIER thread (sink/source state
        may keep mutating once the step loop resumes) and hands the
        frozen bytes to the materializer."""
        faults.inject("ckpt.entries.write", cid=cid)
        tmp = self.path(cid) + ".tmp"
        # clean slate: a stale staging dir (an aborted attempt under the
        # same id, possibly from before a restart) could otherwise leak
        # foreign files — e.g. its manifest.json — into this publish
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        np.savez(os.path.join(tmp, "entries.npz"), **entries)
        if aux_bytes is None:
            aux_bytes = pickle.dumps(
                {"source_offsets": source_offsets, "aux": aux}
            )
        with open(os.path.join(tmp, "aux.pkl"), "wb") as f:
            f.write(aux_bytes)
        meta = {
            "format_version": FORMAT_VERSION,
            "checkpoint_id": cid,
            "timestamp": time.time(),
            **scalars,
        }
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if manifest is not None:
            from flink_tpu.checkpointing import manifest as mf

            nbytes = sum(
                os.path.getsize(os.path.join(tmp, f))
                for f in os.listdir(tmp)
            )
            mf.write_manifest(tmp, {
                **manifest,
                "entries": int(len(entries["key_hi"])),
                "bytes": int(nbytes),
            })
        faults.inject("ckpt.publish", cid=cid)
        final = self.path(cid)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # atomic publish
        if self.local is not None:
            # mirror AFTER the atomic publish: the cache may only ever
            # hold durable cuts (best-effort — a cache failure must not
            # fail the checkpoint)
            self.local.put(cid, final)
        self._gc(keep_latest=cid)
        return final

    def read_manifest(self, cid: int):
        from flink_tpu.checkpointing import manifest as mf

        if (
            self.local is not None
            and self.local.has(cid)
            and self.local.identity_ok(cid)
        ):
            # the manifest is tiny and json.load fails loudly on a torn
            # copy, so it is read without the full-entry CRC sweep —
            # but NOT without the incarnation check: a stale cached
            # manifest would resolve the wrong chain (and _gc computes
            # the live set from chains). Any failure falls to primary.
            try:
                return mf.read_manifest(self.local.path(cid))
            except (OSError, ValueError):
                pass
        return mf.read_manifest(self.path(cid))

    def discard_tmp(self, cid: int) -> None:
        """GC an aborted checkpoint's staging directory. The atomic
        publish means an abort can only ever leave ``chk-<cid>.tmp``
        behind — the published directory set stays exactly the set of
        durable cuts."""
        shutil.rmtree(self.path(cid) + ".tmp", ignore_errors=True)

    def _gc(self, keep_latest: int):
        from flink_tpu.checkpointing import manifest as mf

        cids = self.list_checkpoints()
        others = [c for c in cids if c != keep_latest]
        retained = {keep_latest}
        if self.retain > 1:
            retained.update(others[-(self.retain - 1):])
        # manifest closure: a retained delta keeps its whole chain alive
        live = mf.live_checkpoints(retained, self.read_manifest)
        for cid in cids:
            if cid not in live:
                shutil.rmtree(self.path(cid), ignore_errors=True)
        # stale staging debris: an ABORTED attempt may have left a
        # chk-<X>.tmp behind (e.g. the failed cid differs from the
        # barrier cid that counted the abort). _gc runs on the single
        # thread that executes checkpoint writes — the just-published
        # tmp was already renamed away — so any remaining .tmp dir is
        # an orphan by construction.
        for name in os.listdir(self.dir):
            if name.startswith("chk-") and name.endswith(".tmp"):
                p = os.path.join(self.dir, name)
                if os.path.isdir(p):
                    shutil.rmtree(p, ignore_errors=True)
        if self.local is not None:
            # cache retention follows the SAME chain closure, so the
            # local tier can never offer a cut the primary gave up on
            self.local.prune(live)

    def list_checkpoints(self):
        out = []
        if not os.path.isdir(self.dir):
            return out
        for name in os.listdir(self.dir):
            if name.startswith("chk-") and not name.endswith(".tmp"):
                try:
                    out.append(int(name[4:]))
                except ValueError:
                    pass
        return sorted(out)

    def read(self, cid: int):
        """Logical snapshot at checkpoint ``cid``. A delta checkpoint is
        transparently resolved through its manifest chain (base + deltas,
        last-writer-wins per key group), so callers restore from either
        kind through the same call."""
        m = self.read_manifest(cid)
        if m is not None and m.get("kind") == "delta":
            from flink_tpu.checkpointing.recovery import replay_chain

            return replay_chain(self, cid)
        return self.read_raw(cid)

    def read_raw(self, cid: int):
        """One checkpoint directory's own files, chain-unresolved.
        Prefers the checksum-verified local copy when a cache is
        attached; a miss or a corrupt entry falls back to primary (the
        ``ckpt.read.primary`` injection point models the remote-fetch
        cost the cache exists to avoid)."""
        if self.local is not None:
            from flink_tpu.checkpointing.local import LocalCacheMiss

            try:
                return self._read_raw_dir(self.local.verify(cid), cid)
            except LocalCacheMiss:
                pass
        faults.inject("ckpt.read.primary", cid=cid)
        return self._read_raw_dir(self.path(cid), cid)

    def _read_raw_dir(self, p: str, cid: int):
        try:
            with open(os.path.join(p, "meta.json")) as f:
                meta = json.load(f)
        except OSError as e:
            raise FileNotFoundError(f"checkpoint {cid} unreadable: {e}") \
                from e
        if meta.get("format_version") != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint format: {meta}")
        with np.load(os.path.join(p, "entries.npz")) as z:
            entries = {k: z[k] for k in z.files}
        with open(os.path.join(p, "aux.pkl"), "rb") as f:
            auxd = pickle.load(f)
        scalars = {
            k: meta[k]
            for k in ("watermark", "fired_through", "max_pane", "min_pane",
                      "dropped_late", "dropped_capacity")
        }
        return entries, scalars, auxd["source_offsets"], auxd["aux"]

    def latest(self) -> Optional[int]:
        cids = self.list_checkpoints()
        return cids[-1] if cids else None

    # -- generic (non-window) stage snapshots ---------------------------
    # Heap-backend stages (ProcessFunction, CEP, ...) snapshot pickled
    # key-group blobs instead of device arrays; same chk-<id> layout and
    # retention, different payload file.
    def write_generic(self, cid: int, payload: dict = None,
                      payload_bytes: bytes = None):
        """payload_bytes: pre-pickled payload — the async path serializes
        on the barrier thread and ships frozen bytes (see write())."""
        faults.inject("ckpt.generic.write", cid=cid)
        tmp = self.path(cid) + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)   # stale-attempt debris
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "state.pkl"), "wb") as f:
            if payload_bytes is not None:
                f.write(payload_bytes)
            else:
                pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
        meta = {
            "format_version": FORMAT_VERSION,
            "checkpoint_id": cid,
            "timestamp": time.time(),
            "kind": "generic",
        }
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        final = self.path(cid)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
        if self.local is not None:
            self.local.put(cid, final)
        self._gc(keep_latest=cid)
        return final

    def read_generic(self, cid: int) -> dict:
        p = None
        if self.local is not None:
            from flink_tpu.checkpointing.local import LocalCacheMiss

            try:
                p = self.local.verify(cid)
            except LocalCacheMiss:
                p = None
        if p is None:
            faults.inject("ckpt.read.primary", cid=cid)
            p = self.path(cid)
        with open(os.path.join(p, "meta.json")) as f:
            meta = json.load(f)
        if meta.get("format_version") != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint format: {meta}")
        if meta.get("kind") != "generic":
            raise ValueError(f"checkpoint {cid} is not a generic snapshot")
        with open(os.path.join(p, "state.pkl"), "rb") as f:
            return pickle.load(f)

    # -- incremental key map log ---------------------------------------
    # The codec's key-id -> original-key map is append-only; checkpoints
    # record only a count and new entries go to a shared log, so a 1M-key
    # job doesn't re-pickle the whole map every interval.
    def _keymap_path(self) -> str:
        return os.path.join(self.dir, "keymap.log")

    def append_keymap(self, items) -> None:
        if not items:
            return
        with open(self._keymap_path(), "ab") as f:
            pickle.dump(items, f)

    def read_keymap(self, count: int) -> dict:
        out = {}
        path = self._keymap_path()
        if count and os.path.exists(path):
            with open(path, "rb") as f:
                while len(out) < count:
                    try:
                        for kid, key in pickle.load(f):
                            out.setdefault(kid, key)
                    except EOFError:
                        break
        return out


# ----------------------------------------------------------------- restart

@dataclass
class RestartStrategy:
    """ref RestartStrategies (fixed-delay / failure-rate /
    exponential-delay / no-restart)."""

    kind: str = "none"   # none | fixed-delay | failure-rate | exponential-backoff
    attempts: int = 3
    delay_s: float = 0.0
    failure_rate: int = 3       # max failures...
    failure_interval_s: float = 60.0  # ...per interval
    # exponential-backoff knobs (ref RestartStrategies.
    # exponentialDelayRestart): the delay grows by `multiplier` per
    # consecutive failure up to `max_delay_s`; a failure-free quiet
    # period of `reset_after_s` resets it to `initial_delay_s`; `jitter`
    # is a +-fraction drawn uniformly so fleet-wide restart storms
    # decorrelate. Attempts are UNBOUNDED — the growing delay is the
    # budget.
    initial_delay_s: float = 1.0
    max_delay_s: float = 60.0
    multiplier: float = 2.0
    jitter: float = 0.1
    reset_after_s: float = 3600.0

    _failures: list = None
    _last_failure_t: float = None
    _consecutive: int = 0
    # delays actually slept, newest last (bounded) — the chaos soak
    # asserts bounded backoff from this
    delays: list = None

    @staticmethod
    def none() -> "RestartStrategy":
        return RestartStrategy("none")

    @staticmethod
    def fixed_delay(attempts: int, delay_s: float = 0.0) -> "RestartStrategy":
        return RestartStrategy("fixed-delay", attempts=attempts, delay_s=delay_s)

    @staticmethod
    def failure_rate(max_per_interval: int, interval_s: float,
                     delay_s: float = 0.0) -> "RestartStrategy":
        return RestartStrategy(
            "failure-rate", failure_rate=max_per_interval,
            failure_interval_s=interval_s, delay_s=delay_s,
        )

    @staticmethod
    def exponential_backoff(initial_delay_s: float = 1.0,
                            max_delay_s: float = 60.0,
                            multiplier: float = 2.0,
                            jitter: float = 0.1,
                            reset_after_s: float = 3600.0
                            ) -> "RestartStrategy":
        return RestartStrategy(
            "exponential-backoff", initial_delay_s=initial_delay_s,
            max_delay_s=max_delay_s, multiplier=multiplier, jitter=jitter,
            reset_after_s=reset_after_s,
        )

    def next_backoff_delay(self, now: float = None) -> float:
        """The delay the NEXT exponential-backoff restart would sleep
        (also advances the consecutive-failure bookkeeping)."""
        import random

        now = time.time() if now is None else now
        if (
            self._last_failure_t is not None
            and self.reset_after_s > 0
            and now - self._last_failure_t >= self.reset_after_s
        ):
            self._consecutive = 0       # quiet period: back to initial
        self._last_failure_t = now
        self._consecutive += 1
        delay = min(
            float(self.max_delay_s),
            float(self.initial_delay_s)
            * float(self.multiplier) ** (self._consecutive - 1),
        )
        if self.jitter > 0:
            delay *= 1.0 + random.uniform(-self.jitter, self.jitter)
        return max(0.0, min(delay, float(self.max_delay_s)
                            * (1.0 + self.jitter)))

    def should_restart(self) -> bool:
        now = time.time()
        if self.kind == "none":
            return False
        if self.kind == "exponential-backoff":
            # no _failures ledger: restarts are deliberately unbounded
            # here (the growing delay is the budget), and an append-per-
            # restart list would leak for the lifetime of a crash-
            # looping job — next_backoff_delay keeps all needed state
            # (_last_failure_t/_consecutive)
            delay = self.next_backoff_delay(now)
            if self.delays is None:
                self.delays = []
            self.delays.append(delay)
            del self.delays[:-50]
            if delay:
                time.sleep(delay)
            return True
        if self._failures is None:
            self._failures = []
        self._failures.append(now)
        if self.kind == "fixed-delay":
            ok = len(self._failures) <= self.attempts
        else:
            window = [t for t in self._failures
                      if t > now - self.failure_interval_s]
            self._failures = window
            ok = len(window) <= self.failure_rate
        if ok and self.delay_s:
            time.sleep(self.delay_s)
        return ok
