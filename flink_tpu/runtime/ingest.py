"""Checkpoint-compatible pipelined ingest (the front half of the hot loop).

The windowed step loop decomposes into a *prep* half (source poll, host
chain, key/value/timestamp encode — pure host numpy) and an *apply* half
(watermark advance, device step dispatch, fires). Historically the prep
half could run ahead on a prefetch thread ONLY when no snapshot could
ever be taken: offsets were captured live at the consume point, so a
polled-ahead batch would make a checkpoint skip records on restore. The
production configuration — checkpointing on — therefore serialized
source poll + encode with device compute.

This module makes the overlap checkpoint-compatible and pushes two more
stages of the cycle off the step-loop thread:

* **Epoch-tagged prefetch.** Every prepped batch carries the source
  offsets captured immediately after ITS poll (``Source.
  poll_with_offsets``) plus the pipeline epoch it was prepped under.
  The executor records the offsets of the last *applied* batch; a
  checkpoint/savepoint snapshots those applied offsets, so the cut is
  exactly the state the device has absorbed — in-flight prefetched
  batches are simply dropped on restore (the epoch bump invalidates
  them) and replayed from the rewound source.

* **Async device staging.** With a plan installed (``IngestPlan``, built
  once the stage's compiled steps exist), the prefetch thread pads the
  batch into a preallocated staging ring and ``jax.device_put``s the
  ``hi/lo/ticks/values/valid`` arrays with the route's sharding
  (replicated for the mask route, shard-split on the batch axis for the
  exchange route). The H2D transfer of batch k+1 completes on the
  ingest thread while the device runs the step for batch k; the step
  loop dispatches committed arrays and never pays the pad-copy or the
  transfer enqueue.

* **Off-thread route planning.** The exchange-feasibility check
  (``plan_route`` — the same murmur key-group math the device uses,
  ~2-4 ms of numpy per 262k batch) runs at prep time, reusing its
  key-group computation for the per-(src,dst) bucket fit check, so the
  step loop reads a precomputed route instead of hashing the batch
  again.

Threading contract: ONE producer (the prefetch thread — or the step-loop
thread itself when ``pipeline.prefetch=off``), one consumer (the step
loop). ``pause()``/``resume()`` bracket every source mutation (restore):
pause parks the producer, resume bumps the epoch so queued batches from
the old stream position are discarded by the consumer.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time
from collections import deque
from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.ops.hashing import route_hash
from flink_tpu.parallel.mesh import SHARD_AXIS
from flink_tpu.testing import faults


class IngestThreadDied(RuntimeError):
    """The prefetch producer thread died without delivering a batch or
    an error (hard death — e.g. an injected ``kill`` rule or a native
    crash in the prep path). Classified TRANSIENT at the restart
    boundary: the thread is respawned by the next ``next()`` after a
    restore, so a warm in-process restart fully recovers it."""


# ---------------------------------------------------------------- masks

def make_prefix_mask_template(size: int) -> np.ndarray:
    """One bool template of length 2*size: [True]*size + [False]*size.
    ``prefix_mask(tmpl, n)`` slices a VIEW whose first n lanes are True —
    the per-batch ``np.ones(n) + pad`` allocation becomes one allocation
    per stage. The template is frozen so a view handed to an async
    transfer can never be corrupted by later batches."""
    tmpl = np.zeros(2 * size, bool)
    tmpl[:size] = True
    tmpl.flags.writeable = False
    return tmpl


def prefix_mask(tmpl: np.ndarray, n: int) -> np.ndarray:
    """bool[size] view of `tmpl` with lanes [0, n) True; 0 <= n <= size."""
    size = len(tmpl) >> 1
    return tmpl[size - n: 2 * size - n]


# ------------------------------------------------------------- batches

@dataclasses.dataclass
class PreppedBatch:
    """One prepped micro-batch flowing from the ingest side to the step
    loop. ``offsets`` is the source position captured right after this
    batch's poll — the epoch-tagged replay point; ``epoch`` stamps which
    pipeline incarnation prepped it (batches from a pre-restore epoch
    are dropped by the consumer)."""

    end: bool
    n: int
    now_ms: int
    t_src: float
    offsets: Any = None
    epoch: int = -1
    # host-side encoded arrays (None once staged to device, or when n=0)
    hi: Any = None
    lo: Any = None
    values: Any = None
    ts_ms: Any = None
    # filled by the ingest plan for single-group batches
    ticks: Any = None            # host int32, planned-but-unstaged batches
    ticks_min: Optional[int] = None
    ticks_max: Optional[int] = None
    ts_max: Optional[int] = None
    # "mask" | "exchange" | "sharded" | None (unplanned)
    route: Optional[str] = None
    # device-staged (hi, lo, ticks, values, valid) committed arrays
    staged: Optional[Tuple] = None
    # device batch ring slot sequence (pipeline.resident-loop): set when
    # ``staged`` lives in a DeviceBatchRing slot; the consumer releases
    # the slot once the batch's ring drain retired it. None = staged
    # outside the ring (ring full, or resident loop off).
    ring_seq: Optional[int] = None
    # per-shard slot sequences (pipeline.data-parallel): one entry per
    # shard when ``staged`` lives in a ShardedDeviceBatchRing — a None
    # entry means THAT shard's lane ring was full and its slice was
    # staged fresh (the shard-local backpressure seam); the consumer
    # releases per shard at the drain boundary (release_shards)
    ring_seqs: Optional[list] = None


@dataclasses.dataclass
class IngestPlan:
    """Everything the prep side needs once the stage is set up: the time
    domain, the step lane geometry, the exchange capacity, and the
    shardings each route's compiled step expects its batch arrays in.
    Installed via ``IngestPipeline.set_plan`` after ``setup()`` builds
    the compiled steps (and re-installed on restore — the time-domain
    origin can change); batches prepped before that arrive unplanned and
    take the executor's legacy host-array path."""

    td: Any                      # core.time.TimeDomain
    slide_ticks: int
    span_limit: int              # catch-up slicing threshold (panes)
    B: int                       # micro-batch lane count
    B_step: int                  # step lane count (B padded to shards)
    n_shards: int
    max_parallelism: int
    kg_ends: Any                 # np int32 [n_shards] key-group range ends
    exchange_cap: int            # per-(src,dst) bucket lanes, 0 = no exchange
    routes: Tuple[str, ...]      # available compiled routes
    staging: bool                # device-stage on the ingest thread?
    mask_sharding: Any = None    # replicated batch arrays (mask route)
    split_sharding: Any = None   # batch-axis split (exchange route)
    value_shape: Tuple = ()
    value_dtype: Any = np.float32
    # device batch ring depth (pipeline.resident-loop / ring-depth):
    # > 0 promotes the staging ring to a DeviceBatchRing of this many
    # committed HBM slots; 0 keeps the plain PR 3 staging ring
    ring_depth: int = 0
    # per-shard lane capacity of the data-parallel route (pipeline.
    # data-parallel): > 0 (with "sharded" in ``routes``) promotes the
    # device ring to a ShardedDeviceBatchRing — each batch is host-
    # partitioned by owning key-group slice and published as [n_shards,
    # shard_cap] per-chip lane slices; 0 keeps the global-slot ring
    shard_cap: int = 0

    @staticmethod
    def shardings_for(mesh):
        return NamedSharding(mesh, P()), NamedSharding(mesh, P(SHARD_AXIS))


def plan_route(plan: IngestPlan, hi: np.ndarray, lo: np.ndarray,
               kg: Optional[np.ndarray] = None) -> str:
    """Exact per-batch feasibility of the ICI exchange, at prep time.

    Computes every lane's owning shard (the same murmur key-group math
    the device uses) and picks the O(B/n)-per-device all_to_all step
    only when each (source device, dest shard) bucket provably fits its
    static capacity — skew falls back to replicate-and-mask, so the
    adaptive route is never lossy. Runs on the UNPADDED arrays: padding
    lanes are invalid on device and lane i's source device is i//bpd
    either way, so the counts match the padded check exactly. ``kg``
    lets a caller that already computed the key groups (the sharded-
    route planner) skip the second murmur pass."""
    if "exchange" not in plan.routes:
        return "mask"
    if "mask" not in plan.routes:
        return "exchange"        # exchange.mode=all_to_all forced
    n = plan.n_shards
    if kg is None:
        kg = assign_to_key_group(route_hash(hi, lo, np),
                                 plan.max_parallelism, np)
    shard = np.searchsorted(plan.kg_ends, kg)
    bpd = plan.B_step // n
    src = np.arange(len(hi)) // bpd
    counts = np.bincount(src * n + shard, minlength=n * n)
    return (
        "exchange" if counts.max(initial=0) <= plan.exchange_cap
        else "mask"
    )


def plan_route_and_shards(
    plan: IngestPlan, hi: np.ndarray, lo: np.ndarray
) -> Tuple[str, Optional[np.ndarray]]:
    """Data-parallel route plan (pipeline.data-parallel): ONE key-group
    pass decides the route AND hands back every lane's owning shard.

    The sharded route is feasible when each shard's slice of the batch
    fits its static per-shard lane capacity (``plan.shard_cap``) — the
    host then partitions the batch and each chip receives only its own
    O(cap) lanes. A batch too skewed to fit falls back to the ordinary
    ``plan_route`` choice (reusing the computed key groups), so the
    adaptive ladder is sharded -> exchange -> mask and never lossy."""
    kg = assign_to_key_group(route_hash(hi, lo, np), plan.max_parallelism,
                             np)
    if "sharded" in plan.routes and plan.shard_cap > 0:
        shard = np.searchsorted(plan.kg_ends, kg)
        counts = np.bincount(shard, minlength=plan.n_shards)
        if counts.max(initial=0) <= plan.shard_cap:
            return "sharded", shard
    return plan_route(plan, hi, lo, kg=kg), None


def _route_sharding(plan: IngestPlan, route: str):
    # sharded batches are [n_shards, cap] arrays split on the leading
    # (shard) axis — the same P(SHARD_AXIS) sharding the exchange route
    # uses on its batch axis
    return (
        plan.split_sharding if route in ("exchange", "sharded")
        else plan.mask_sharding
    )


def stage_batch_arrays(plan: IngestPlan, route: str, hi, lo, ticks,
                       values, valid) -> Tuple:
    """Step-loop-thread staging of already-padded FRESH arrays (the
    executor's fallback call sites: warmup, catch-up slices, chunked
    polls). Non-blocking — the transfer is enqueued and the arrays are
    never reused by the caller, so there is no buffer-recycle hazard.
    Exists so every update dispatch feeds the compiled step committed
    arrays of the SAME sharding: mixing committed and uncommitted inputs
    would recompile the step mid-stream."""
    sh = _route_sharding(plan, route)
    return tuple(
        jax.device_put(x, sh) for x in (hi, lo, ticks, values, valid)
    )


def _host_probe_put_aliases(buf: np.ndarray, sharding) -> bool:
    """One-time ring-init probe (host-side by contract): does
    ``jax.device_put`` of THIS buffer alias its memory instead of
    copying?  XLA's CPU client zero-copies suitably-aligned host
    buffers — the "staged" array then IS the buffer, and recycling the
    slot would corrupt every batch still referencing it. Aliasing is
    decided per allocation (alignment), so each slot buffer is probed
    individually. Mutates one lane and restores it."""
    flat = buf.reshape(-1)
    d = jax.device_put(flat[:1], sharding)
    jax.block_until_ready(d)
    old = flat[0]
    flat[0] = 1 if old == 0 else 0
    aliased = bool(np.asarray(d)[0] != old)
    flat[0] = old
    return aliased


# per-PROCESS zero-copy aliasing verdicts (ISSUE 12 small fix): the
# probe used to run per ring init — per JOB — so bench sweeps and test
# suites that build dozens of pipelines in one process paid the device
# round trips over and over. The verdict is a property of the backend's
# device_put path, not of the job, so it is cached process-wide:
#
#   * non-CPU platforms skip the probe entirely — an accelerator
#     device_put is architecturally an H2D copy into HBM; host memory
#     can never alias it.
#   * on CPU only the ALIASED verdict is sticky: one observed zero-copy
#     proves the client takes that path, and disabling slot reuse (the
#     consequence) is the safe direction for every later ring. An
#     all-False probe is NOT cached — aliasing is decided per
#     allocation (alignment), so a later ring's differently-aligned
#     buffers could still alias, and caching False there is exactly the
#     silent-corruption direction the probe exists to prevent.
_put_alias_sticky: dict = {}


def _host_put_aliases_cached(bufs, sharding) -> bool:
    platform = jax.default_backend()
    if platform != "cpu":
        return False
    if _put_alias_sticky.get(platform):
        return True
    aliased = any(_host_probe_put_aliases(b, sharding) for b in bufs)
    if aliased:
        _put_alias_sticky[platform] = True
    return aliased


class StagingRing:
    """Preallocated host padding buffers for the prefetch thread's
    device staging — the per-batch ``np.zeros`` padding in ``_pad``
    becomes a write into a recycled slot. A slot is reused only after
    its transfer COMPLETED: ``stage()`` blocks on the put, on the ingest
    thread, so the step loop never waits and the recycled bytes can
    never race an in-flight H2D copy. Depth 2 double-buffers (one slot
    being written while the previous one finishes transferring).

    Backends whose ``device_put`` ZERO-COPIES host memory (XLA CPU with
    aligned buffers) make recycling impossible: the staged array aliases
    the slot forever, so ``stage()`` detects that at init (per-buffer
    probe) and falls back to fresh per-batch buffers there — on such
    backends there is no H2D copy to overlap anyway, so the ring's only
    job is correctness."""

    def __init__(self, plan: IngestPlan, depth: int = 2):
        Bs = plan.B_step
        vshape = (Bs,) + tuple(plan.value_shape)

        def one_slot():
            return {
                "hi": np.zeros(Bs, np.uint32),
                "lo": np.zeros(Bs, np.uint32),
                "ticks": np.zeros(Bs, np.int32),
                "values": np.zeros(vshape, plan.value_dtype),
            }

        self._make_slot = one_slot
        self._slots = [one_slot() for _ in range(max(2, int(depth)))]
        self._i = 0
        self._mask_tmpl = make_prefix_mask_template(Bs)
        self._reuse = not _host_put_aliases_cached(
            [buf for slot in self._slots for buf in slot.values()],
            plan.mask_sharding,
        )

    @staticmethod
    def _fill(buf: np.ndarray, arr: np.ndarray, n: int) -> np.ndarray:
        if len(arr) == len(buf):
            return arr           # full batch: fresh array, ship directly
        buf[:n] = arr
        buf[n:] = 0
        return buf

    def stage(self, plan: IngestPlan, hi, lo, ticks, values, n: int,
              route: str, tracer=None) -> Tuple:
        """Pad into the next ring slot and device_put with the route's
        sharding; returns committed (hi, lo, ticks, values, valid)."""
        if self._reuse:
            slot = self._slots[self._i]
            self._i = (self._i + 1) % len(self._slots)
        else:
            # zero-copy backend: the staged array will alias whatever we
            # hand it — hand it single-use buffers, never the ring's
            slot = self._make_slot()
        t0 = time.perf_counter()
        srcs = (
            self._fill(slot["hi"], hi, n),
            self._fill(slot["lo"], lo, n),
            self._fill(slot["ticks"], ticks, n),
            self._fill(slot["values"], values, n),
            prefix_mask(self._mask_tmpl, n),
        )
        t_pad = time.perf_counter()
        sh = _route_sharding(plan, route)
        staged = tuple(jax.device_put(x, sh) for x in srcs)
        # transfer completion ON THE INGEST THREAD: the slot may be
        # recycled the moment the device owns the bytes, and the step
        # loop receives arrays it can dispatch without ever waiting
        jax.block_until_ready(staged)  # host-sync-ok: ingest-thread transfer completion, off the step loop
        if tracer is not None and tracer.active:
            tracer.rec("stage", t0, t_pad, n=n)
            tracer.rec("transfer", t_pad, route=route)
        return staged


class DeviceBatchRing:
    """Device-resident batch ring (pipeline.resident-loop, ISSUE 12):
    PR 3's staging ring promoted to a bounded ring of COMMITTED device
    batch slots with a host-side write cursor, so the step loop can see
    "how many staged batches are ready right now" and retire them all
    with one resident-drain dispatch (runtime/step.py
    build_window_resident_drain) instead of one megastep each.

    Layout: ``depth`` slots, each pairing one preallocated host padding
    buffer set (the embedded StagingRing, sized to the ring so every
    in-flight slot has its own pad buffers) with the committed device
    arrays staged through it. A slot is (seq, epoch, staged 5-tuple);
    the staged arrays are the slot's HBM residency — publishing bounds
    the device footprint to ``depth`` batches, and releasing a slot
    drops the last reference so the arrays free as soon as the drain
    that consumed them retires. (JAX owns physical allocation; the ring
    owns the lifetime, which is the half a host-side cursor can pin.)

    Threading contract (SPSC, same as the pipeline): ONE producer — the
    prefetch thread — publishes; ONE consumer — the step loop — reads
    occupancy and releases. ``try_publish`` is the producer's whole
    surface: it stages into the next slot and advances the write cursor,
    or returns None when the ring is full (the caller falls back to
    plain staging, so a slow drain never blocks the source poll). The
    write cursor is advanced AFTER the slot contents are in place, so
    the consumer can never observe a half-published slot; cursors are
    plain ints mutated under one lock (the critical sections are
    pointer-sized — the cursor-race property test drives this seam).

    Epoch discard: every slot carries the pipeline epoch it was staged
    under. ``clear()`` (called from the pipeline's restore ``resume``,
    after ``pause`` parked the producer) retires every in-flight slot —
    the epoch bump already invalidates the queued PreppedBatches that
    reference them, and the rewound source replays those records."""

    sharded = False    # ShardedDeviceBatchRing overrides

    def __init__(self, plan: IngestPlan, depth: int):
        self.depth = max(2, int(depth))
        self._staging = StagingRing(plan, self.depth)
        self._slots: list = [None] * self.depth
        self._write = 0          # seq of the next slot to publish
        self._read = 0           # seq of the oldest unreleased slot
        self._refusals = 0       # full-ring publish refusals (backpressure)
        # drain flight recorder (observability.drain-stats): publish-time
        # stamps (shard, seq, fill, max_tick, t) appended in the locked
        # commit below and drained by the executor's consume path; the
        # executor flips stats_enabled so the default path appends nothing
        self.stats_enabled = False
        self._pub_samples: deque = deque(maxlen=4096)
        # device publish cursor (pipeline.resident-loop=while, ISSUE 20):
        # a tiny HBM int32 slot mirroring the host write cursor. The
        # ingest thread refreshes it after every commit; the while-drain
        # dispatch takes the freshest copy (donated, so an aliasing
        # runtime reuses the same HBM slot) and its loop condition
        # re-reads it — a batch published mid-drain retires in the same
        # dispatch. Disabled (None) unless the executor opts in.
        self._cursor_sharding = None
        self._cursor = None
        self._lock = threading.Lock()

    def enable_device_cursor(self, sharding) -> None:
        """Opt in to the HBM publish cursor (while-drain mode). The
        sharding is the replicated scalar-slot sharding the while-drain
        kernel expects for its ``cursor`` operand."""
        with self._lock:
            self._cursor_sharding = sharding
            self._cursor = jax.device_put(
                np.full(1, self._write, np.int32), sharding)

    def device_cursor(self):
        """``(cursor, write_snapshot)`` — the freshest device-resident
        publish cursor (int32[1]) plus the host write seq it encodes
        (read under the same lock, so the pair is consistent) — or None
        when the cursor slot is disabled. The caller passes the array
        straight into the while-drain dispatch and derives the drain
        base from the snapshot; the array is replaced (never mutated)
        on every commit, so a grabbed reference is a stable snapshot
        lower-bounding the live value."""
        with self._lock:
            if self._cursor is None:
                return None
            return self._cursor, self._write

    def refresh_device_cursor(self) -> None:
        """Re-stage the cursor slot (the consumer calls this right after
        a while-drain dispatch donated the grabbed array, so a quiet
        stream's NEXT drain never re-passes a deleted buffer)."""
        with self._lock:
            if self._cursor_sharding is not None:
                self._cursor = jax.device_put(
                    np.full(1, self._write, np.int32),
                    self._cursor_sharding)

    # -- producer (prefetch thread) --------------------------------------
    def try_publish(self, plan: IngestPlan, hi, lo, ticks, values,
                    n: int, route: str, epoch: int,
                    tracer=None) -> Optional[Tuple[int, Tuple]]:
        """Stage one batch into the next ring slot; returns (seq,
        staged) or None when the ring is full. The stage itself blocks
        for transfer completion on THIS thread (StagingRing.stage), so a
        published slot's arrays are always dispatch-ready."""
        with self._lock:
            if self._write - self._read >= self.depth:
                # counted, not silent: the ring_publish_refusals gauge
                # makes a stalled drain observable as backpressure
                # instead of an unexplained throughput dip
                self._refusals += 1
                return None
            seq = self._write
        staged = self._staging.stage(plan, hi, lo, ticks, values, n,
                                     route, tracer=tracer)
        max_tick = int(ticks[:n].max()) if n else None
        with self._lock:
            self._slots[seq % self.depth] = (seq, epoch, staged)
            self._write = seq + 1
            if self._cursor_sharding is not None:
                # refresh the HBM cursor slot AFTER the commit so the
                # device can never see a cursor covering a slot whose
                # payload isn't resident yet (the while-drain's staged
                # clamp guards the packed-operand side)
                self._cursor = jax.device_put(
                    np.full(1, self._write, np.int32),
                    self._cursor_sharding)
            if self.stats_enabled:
                self._pub_samples.append((
                    0, seq, self._write - self._read, max_tick,
                    time.perf_counter(),
                ))
        return seq, staged

    # -- consumer (step loop) --------------------------------------------
    def occupancy(self) -> int:
        """Committed-but-unreleased slots: write cursor - read cursor."""
        with self._lock:
            return self._write - self._read

    def release_through(self, seq: int) -> int:
        """Retire every slot up to and including ``seq`` (a drain
        returned for them — the ring-drain exactly-once boundary).
        Returns the number of slots released. Out-of-window seqs are a
        no-op: a restore's ``clear`` may already have retired them."""
        with self._lock:
            if seq < self._read:
                return 0
            upto = min(seq, self._write - 1)
            n = upto - self._read + 1
            for s in range(self._read, upto + 1):
                self._slots[s % self.depth] = None
            self._read = upto + 1
            return n

    def clear(self) -> int:
        """Restore path: discard every in-flight slot (their epoch is
        pre-bump; the queued batches referencing them are dropped by the
        consumer's epoch check and replay from the rewound source)."""
        with self._lock:
            n = self._write - self._read
            self._slots = [None] * self.depth
            self._read = self._write
            return n

    def refusals(self) -> list:
        """Per-shard full-ring publish refusal counts (one entry here —
        the global-slot ring has a single lane); the executor surfaces
        the sum and the per-shard breakdown as gauges."""
        with self._lock:
            return [self._refusals]

    def occupancy_shards(self) -> list:
        """Per-lane committed-but-unreleased counts (one lane here)."""
        with self._lock:
            return [self._write - self._read]

    def publish_samples(self) -> list:
        """Drain the publish-time stamp buffer (drain flight recorder);
        empty unless the executor enabled ``stats_enabled``."""
        with self._lock:
            out = list(self._pub_samples)
            self._pub_samples.clear()
        return out


class ShardedDeviceBatchRing:
    """Per-shard device batch ring (pipeline.data-parallel, ISSUE 13):
    the DeviceBatchRing split into ``n_shards`` independent lanes. The
    prefetch thread partitions each planned batch by owning key-group
    slice (one stable-sort pass — stable so a key's records keep their
    arrival order and float accumulation is bit-exact vs the single-chip
    oracle), pads each shard's slice into that shard's ring slot, and
    device_puts the (1, cap) row DIRECTLY onto the owning chip. The
    per-slot global [n_shards, cap] arrays are then assembled ZERO-COPY
    from the committed rows (jax.make_array_from_single_device_arrays)
    under the split sharding the sharded drain kernel expects — no chip
    ever receives another chip's lanes, on the wire or in HBM.

    Per-shard write/read cursors are the "one slow shard never blocks
    the others" seam: a full lane refuses ONLY its own shard's row
    (counted in that shard's refusal counter; the row is staged fresh,
    unringed, and its ``ring_seqs`` entry is None), while every other
    shard's row still publishes into its recycled slot. The consumer
    releases per shard at ring-drain boundaries (``release_shards``
    with the drained per-shard sequence vector).

    Threading contract is the DeviceBatchRing's: one producer (prefetch
    thread) publishes, one consumer (step loop) releases; cursors are
    plain ints under one lock."""

    sharded = True

    def __init__(self, plan: IngestPlan, depth: int):
        self.depth = max(2, int(depth))
        self.n_shards = plan.n_shards
        self.cap = int(plan.shard_cap)
        vshape = (self.cap,) + tuple(plan.value_shape)
        mesh = plan.split_sharding.mesh
        self._devices = list(mesh.devices.flat)
        self._split = plan.split_sharding
        self._vdtype = plan.value_dtype

        def one_slot():
            return {
                "hi": np.zeros(self.cap, np.uint32),
                "lo": np.zeros(self.cap, np.uint32),
                "ticks": np.zeros(self.cap, np.int32),
                "values": np.zeros(vshape, plan.value_dtype),
            }

        self._make_slot = one_slot
        # per-shard slot buffer pools + cursors; a slot pins its rows'
        # lifetime (the committed global array holds the same buffers)
        self._bufs = [
            [one_slot() for _ in range(self.depth)]
            for _ in range(self.n_shards)
        ]
        self._slots = [[None] * self.depth for _ in range(self.n_shards)]
        self._write = [0] * self.n_shards
        self._read = [0] * self.n_shards
        self._refusals = [0] * self.n_shards
        # drain flight recorder stamps — see DeviceBatchRing
        self.stats_enabled = False
        self._pub_samples: deque = deque(maxlen=4096)
        # per-shard device publish cursor (while-drain mode) — int32
        # [n_shards] under the shard axis; see DeviceBatchRing
        self._cursor_sharding = None
        self._cursor = None
        self._lock = threading.Lock()
        self._mask_tmpl = make_prefix_mask_template(self.cap)
        self._reuse = not _host_put_aliases_cached(
            [b for pool in self._bufs for slot in pool
             for b in slot.values()],
            plan.mask_sharding,
        )

    def enable_device_cursor(self, sharding) -> None:
        """Opt in to the per-shard HBM publish cursor (while-drain
        mode); ``sharding`` places int32[n_shards] one entry per owning
        chip (shard axis)."""
        with self._lock:
            self._cursor_sharding = sharding
            self._cursor = jax.device_put(
                np.fromiter(self._write, np.int32, self.n_shards),
                sharding)

    def device_cursor(self):
        """``(cursor, write_snapshots)`` — the freshest per-shard
        publish cursor (int32[n_shards]) plus the per-shard host write
        seqs it encodes — or None; see DeviceBatchRing.device_cursor."""
        with self._lock:
            if self._cursor is None:
                return None
            return self._cursor, tuple(self._write)

    def refresh_device_cursor(self) -> None:
        """Re-stage the per-shard cursor after a while-drain dispatch
        donated the grabbed array; see DeviceBatchRing."""
        with self._lock:
            if self._cursor_sharding is not None:
                self._cursor = jax.device_put(
                    np.fromiter(self._write, np.int32, self.n_shards),
                    self._cursor_sharding)

    @staticmethod
    def _fill(buf: np.ndarray, arr: np.ndarray, c: int) -> np.ndarray:
        buf[:c] = arr
        buf[c:] = 0
        return buf

    # -- producer (prefetch thread) --------------------------------------
    def publish_batch(self, plan: IngestPlan, hi, lo, ticks, values,
                      shard: np.ndarray, n: int, epoch: int,
                      tracer=None) -> Tuple[list, Tuple]:
        """Partition one planned batch by owning shard and publish each
        slice into that shard's ring lane. Returns ``(ring_seqs,
        staged)``: per-shard slot sequences (None where that lane was
        full and the row went out fresh) and the committed global
        [n_shards, cap] 5-tuple the sharded drain consumes. Never
        refuses the whole batch — the global-array contract needs every
        shard's row either way, so a full lane costs one fresh
        allocation, not a stall."""
        t0 = time.perf_counter()
        order = np.argsort(shard[:n], kind="stable")
        counts = np.bincount(shard[:n], minlength=self.n_shards)
        srcs = (hi[order], lo[order], ticks[order], values[order])
        seqs: list = [None] * self.n_shards
        rows = ([], [], [], [], [])
        pos = 0
        for s in range(self.n_shards):
            c = int(counts[s])
            with self._lock:
                if self._write[s] - self._read[s] < self.depth:
                    seqs[s] = self._write[s]
                else:
                    self._refusals[s] += 1
            if self._reuse and seqs[s] is not None:
                bufs = self._bufs[s][seqs[s] % self.depth]
            else:
                # zero-copy backend or full lane: single-use buffers
                bufs = self._make_slot()
            filled = (
                self._fill(bufs["hi"], srcs[0][pos:pos + c], c),
                self._fill(bufs["lo"], srcs[1][pos:pos + c], c),
                self._fill(bufs["ticks"], srcs[2][pos:pos + c], c),
                self._fill(bufs["values"], srcs[3][pos:pos + c], c),
                prefix_mask(self._mask_tmpl, c),
            )
            pos += c
            dev = self._devices[s]
            for j, x in enumerate(filled):
                # (1, cap) row committed onto the OWNING chip only
                rows[j].append(jax.device_put(x[None], dev))
        t_pad = time.perf_counter()
        staged = tuple(
            jax.make_array_from_single_device_arrays(
                (self.n_shards,) + r[0].shape[1:], self._split, r,
            )
            for r in rows
        )
        # transfer completion ON THE INGEST THREAD (StagingRing.stage
        # contract): a published slot's rows are dispatch-ready
        jax.block_until_ready(staged)  # host-sync-ok: ingest-thread transfer completion, off the step loop
        max_tick = int(ticks[:n].max()) if n else None
        t_pub = time.perf_counter()
        with self._lock:
            for s in range(self.n_shards):
                if seqs[s] is not None:
                    self._slots[s][seqs[s] % self.depth] = (
                        seqs[s], epoch, tuple(r[s] for r in rows),
                    )
                    self._write[s] = seqs[s] + 1
                if self.stats_enabled:
                    self._pub_samples.append((
                        s, seqs[s], self._write[s] - self._read[s],
                        max_tick, t_pub,
                    ))
            if self._cursor_sharding is not None:
                # post-commit refresh; see DeviceBatchRing.try_publish
                self._cursor = jax.device_put(
                    np.fromiter(self._write, np.int32, self.n_shards),
                    self._cursor_sharding)
        if tracer is not None and tracer.active:
            tracer.rec("stage", t0, t_pad, n=n)
            tracer.rec("transfer", t_pad, route="sharded")
        return seqs, staged

    # -- consumer (step loop) --------------------------------------------
    def occupancy(self) -> int:
        """Deepest lane's committed-but-unreleased slot count."""
        with self._lock:
            return max(
                self._write[s] - self._read[s]
                for s in range(self.n_shards)
            )

    def release_shards(self, seqs) -> int:
        """Retire each shard's slots up to and including ``seqs[s]`` (a
        drain returned for them — the per-shard exactly-once boundary).
        None entries (that shard published nothing ringed in the
        drained group) and out-of-window seqs are no-ops. Returns total
        slots released."""
        total = 0
        with self._lock:
            for s, seq in enumerate(seqs):
                if seq is None or seq < self._read[s]:
                    continue
                upto = min(int(seq), self._write[s] - 1)
                for q in range(self._read[s], upto + 1):
                    self._slots[s][q % self.depth] = None
                total += upto - self._read[s] + 1
                self._read[s] = upto + 1
        return total

    def release_through(self, seq: int) -> int:
        """Uniform release — every shard through ``seq`` (fallback call
        sites that only track a scalar frontier)."""
        return self.release_shards([seq] * self.n_shards)

    def clear(self) -> int:
        """Restore path: discard every lane's in-flight slots (epoch
        bump invalidated the batches referencing them)."""
        with self._lock:
            n = sum(
                self._write[s] - self._read[s]
                for s in range(self.n_shards)
            )
            self._slots = [
                [None] * self.depth for _ in range(self.n_shards)
            ]
            self._read = list(self._write)
            return n

    def refusals(self) -> list:
        """Per-shard full-lane publish refusal counts."""
        with self._lock:
            return list(self._refusals)

    def occupancy_shards(self) -> list:
        """Per-shard committed-but-unreleased slot counts."""
        with self._lock:
            return [
                self._write[s] - self._read[s]
                for s in range(self.n_shards)
            ]

    def publish_samples(self) -> list:
        """Drain the publish-time stamp buffer (drain flight recorder);
        empty unless the executor enabled ``stats_enabled``."""
        with self._lock:
            out = list(self._pub_samples)
            self._pub_samples.clear()
        return out


# ------------------------------------------------------- fused dispatch

class FusedBatchAccumulator:
    """Fused-dispatch slot for ``pipeline.steps-per-dispatch=K``: collects
    up to K consecutive planned micro-batches that share a route and a
    staging mode, which the executor then hands to ONE compiled lax.scan
    megastep (runtime/step.py build_window_megastep*). The flush triggers
    — route change, checkpoint/savepoint cut, idle poll, end of stream,
    restore, and (split-dispatch mode only) fire boundary — are all
    step-loop state, so the executor drives; this class owns the slot
    bookkeeping so the grouping contract is unit-testable.

    ``hold_fires`` records the resident-pipeline mode
    (pipeline.fused-fire): the fire sweep is folded into the megastep
    scan, so a pane-boundary crossing inside the group no longer breaks
    it — groups stay K-full across fire boundaries and the in-scan
    advance fires each sub-batch under its own watermark. With it off
    (the PR-5 split-dispatch behavior, still the partial-group and DCN
    fallback) the executor flushes early at every fire boundary so the
    separate fire dispatch sees every pending update.

    Exactly-once contract: a batch sitting in the slot has NOT been
    dispatched, so its offsets must not become the applied cut until the
    flush — the executor marks the LAST flushed batch applied, which is
    the megastep-boundary snapshot cut."""

    def __init__(self, k: int, hold_fires: bool = False):
        self.k = max(1, int(k))
        self.hold_fires = bool(hold_fires)
        self.items: list = []      # [(args 5-tuple, wm_ms | None, pb)]
        self.route: Optional[str] = None
        self.staged: Optional[bool] = None

    def __len__(self):
        return len(self.items)

    def compatible(self, route: str, staged: bool) -> bool:
        """Can a batch of this route/staging mode join the open group?"""
        return not self.items or (
            route == self.route and staged == self.staged
        )

    def push(self, args: Tuple, wm_ms, pb, route: str, staged: bool):
        if not self.items:
            self.route, self.staged = route, staged
        self.items.append((args, wm_ms, pb))

    def full(self) -> bool:
        return len(self.items) >= self.k

    def drain(self):
        """Take the group: (route, staged, items). Resets the slot."""
        items, self.items = self.items, []
        route, staged = self.route, self.staged
        self.route = self.staged = None
        return route, staged, items

    def clear(self):
        """Restore path: pending batches belong to the pre-restore epoch
        — they are discarded and replay from the rewound source."""
        self.items = []
        self.route = self.staged = None


# ------------------------------------------------------------- pipeline

class IngestPipeline:
    """Single-producer single-consumer prep pipeline with restore-safe
    epochs.

    * ``next()`` — the step loop's batch intake. With prefetch on it
      drains the bounded queue (stale-epoch batches are skipped,
      producer errors re-raise on the consumer); with prefetch off it
      runs the prep function inline. Either way the batch is finished
      against the current plan (route planned, optionally staged).
    * ``mark_applied(pb)`` — the step loop calls this once a batch's
      updates are dispatched; ``applied_offsets()`` then names the cut a
      checkpoint/savepoint must snapshot.
    * ``pause()`` / ``resume(offsets)`` — bracket source mutation
      (restore). Pause parks the producer (waits until it is off the
      source); resume bumps the epoch, drops queued batches, re-arms the
      applied cut, and unparks.

    The producer parks itself after delivering an end-of-stream batch or
    an error instead of exiting: a restore may rewind the source past
    either, and ``resume`` simply continues the same thread.
    """

    def __init__(self, prep_fn: Callable[[], PreppedBatch], *,
                 prefetch: bool, initial_offsets: Any = None,
                 depth: int = 2, ring_depth: int = 2, tracer=None):
        self.prep_fn = prep_fn
        self.prefetch = bool(prefetch)
        self.tracer = tracer
        # serializes SOURCE WIRE interactions: the producer holds it
        # across each poll, and the executor takes it around checkpoint-
        # complete notifications (offset commits may share the poll's
        # connection — e.g. the partitioned socket consumers — and an
        # interleaved commit mid-fetch would corrupt the protocol)
        self.source_lock = threading.RLock()
        self._plan: Optional[IngestPlan] = None
        self._ring: Optional[StagingRing] = None
        self._device_ring: Optional[DeviceBatchRing] = None
        self._ring_depth = max(2, int(ring_depth))
        self._applied = initial_offsets
        self._epoch = 0
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        self._stop = threading.Event()
        self._gate = threading.Event()   # producer runs while set
        self._pause_req = threading.Event()  # consumer-requested pause
        self._parked = threading.Event()
        self._gate.set()
        self._thread: Optional[threading.Thread] = None
        # epoch the live thread was spawned under: a DEAD thread is only
        # respawned after a restore bumped the epoch (see _ensure_thread)
        self._thread_epoch = -1

    # -- plan ------------------------------------------------------------
    @property
    def plan(self) -> Optional[IngestPlan]:
        return self._plan

    def set_plan(self, plan: IngestPlan):
        """Install/replace the prep plan (attribute publish is atomic;
        batches mid-prep finish under whichever plan they started —
        the consumer handles both planned and unplanned batches). With
        ``plan.ring_depth > 0`` the plan also stands up the device batch
        ring; the plain staging ring stays as the ring-full fallback."""
        if plan.staging:
            self._ring = StagingRing(plan, self._ring_depth)
            if plan.ring_depth > 0:
                # data-parallel mode: per-shard lane rings (re-sliced on
                # every set_plan — the elastic re-plan installs a plan
                # at the new n_shards and gets fresh lanes for free)
                ring_cls = (
                    ShardedDeviceBatchRing
                    if plan.shard_cap > 0 and "sharded" in plan.routes
                    else DeviceBatchRing
                )
                self._device_ring = ring_cls(plan, plan.ring_depth)
            else:
                self._device_ring = None
        else:
            self._ring = None
            self._device_ring = None
        self._plan = plan

    @property
    def device_ring(self) -> Optional[DeviceBatchRing]:
        return self._device_ring

    def _finish(self, pb: PreppedBatch) -> PreppedBatch:
        """Apply the plan to a freshly prepped batch: time-domain ticks,
        pane-span eligibility, route choice, optional device staging.
        Ineligible batches (catch-up spans, host-chain expansion beyond
        B, foreign value dtype) pass through unplanned and take the
        executor's general path."""
        plan = self._plan
        if plan is None or pb.n == 0:
            return pb
        pb.ts_max = int(pb.ts_ms.max())
        ticks = plan.td.to_ticks(pb.ts_ms)
        t_min, t_max = int(ticks.min()), int(ticks.max())
        values = pb.values
        eligible = (
            pb.n <= plan.B
            and (t_max // plan.slide_ticks) - (t_min // plan.slide_ticks)
            < plan.span_limit
            and isinstance(values, np.ndarray)
            and values.dtype == plan.value_dtype
            and values.shape[1:] == tuple(plan.value_shape)
        )
        if not eligible:
            return pb
        pb.ticks_min, pb.ticks_max = t_min, t_max
        t_r0 = time.perf_counter()
        dr = self._device_ring
        shard_of = None
        if dr is not None and dr.sharded:
            # ONE key-group pass plans the route and the partition
            pb.route, shard_of = plan_route_and_shards(plan, pb.hi, pb.lo)
        else:
            pb.route = plan_route(plan, pb.hi, pb.lo)
        tracer = self.tracer
        if tracer is not None and tracer.active:
            tracer.rec("route", t_r0, route=pb.route, planned=True)
        if self._ring is not None:
            pub = None
            if shard_of is not None:
                # data-parallel publish: per-shard slices into per-shard
                # lanes (never refuses the batch — a full lane only
                # costs its own shard a fresh row)
                pb.ring_seqs, pb.staged = dr.publish_batch(
                    plan, pb.hi, pb.lo, ticks, values, shard_of, pb.n,
                    pb.epoch, tracer=tracer,
                )
                pub = (None, pb.staged)
            elif dr is not None and not dr.sharded:
                pub = dr.try_publish(
                    plan, pb.hi, pb.lo, ticks, values, pb.n, pb.route,
                    pb.epoch, tracer=tracer,
                )
                if pub is not None:
                    pb.ring_seq, pb.staged = pub
            if pub is None:
                # device ring full (or resident loop off): plain staging
                # — the batch still flows in order through the queue,
                # and the drain dispatcher applies it as an unringed
                # staged batch, so a slow drain backpressures HBM
                # residency without ever blocking the source poll
                pb.staged = self._ring.stage(
                    plan, pb.hi, pb.lo, ticks, values, pb.n, pb.route,
                    tracer=tracer,
                )
            # the ring slot owns the padded copies; drop the host arrays
            # so nothing can alias a recycled slot
            pb.hi = pb.lo = pb.values = None
            pb.ticks = None
        else:
            pb.ticks = ticks
        return pb

    # -- producer --------------------------------------------------------
    def _producer(self):
        while not self._stop.is_set():
            if not self._gate.is_set():
                self._parked.set()
                self._gate.wait(0.1)
                continue
            self._parked.clear()
            # chaos seam, OUTSIDE the delivery try: an injected raise
            # kills the thread WITHOUT handing the consumer an error —
            # the "prefetch thread died" detection path in next() (and
            # the ensure-thread respawn) is exactly what it exercises
            faults.inject("ingest.producer", epoch=self._epoch)
            epoch = self._epoch
            park_after = False
            try:
                with self.source_lock:
                    pb = self.prep_fn()
                pb.epoch = epoch
                self._finish(pb)
                item = ("ok", epoch, pb)
                park_after = pb.end
            except Exception as e:   # deliver to the consumer
                # BaseException (ThreadKilled, interpreter teardown) is
                # NOT delivered: it kills the producer hard, which is
                # the dead-thread detection path next() covers
                item = ("err", epoch, e)
                park_after = True
            if park_after:
                # park BEFORE publishing: the consumer may pause+resume
                # (restore) the instant it sees the item, and resume
                # must find the producer already off the source
                self._gate.clear()
            self._put(item)
        self._parked.set()

    def _put(self, item):
        while not self._stop.is_set():
            if self._pause_req.is_set():
                # consumer is pausing: the epoch is being invalidated and
                # the consumer would skip this item anyway — drop rather
                # than deadlock on a full queue while pause() waits
                return
            try:
                self._q.put(item, timeout=0.05)
                return
            except queue.Full:
                continue

    def _ensure_thread(self):
        if self._thread is not None and not self._thread.is_alive():
            if self._thread_epoch == self._epoch:
                # hard death (not a restore respawn): the thread may have
                # died MID-POLL, advancing the source past records it
                # never delivered — silently respawning would turn that
                # into data loss. Surface it; the restart machinery
                # restores to the applied-offset cut and the epoch bump
                # below then legitimizes a fresh producer.
                raise IngestThreadDied(
                    "ingest prefetch thread died without delivering a "
                    "batch or an error"
                )
            self._thread = None
        if self._thread is None:
            t = threading.Thread(
                target=self._producer, daemon=True,
                name="flink-tpu-ingest",
            )
            self._thread = t
            self._thread_epoch = self._epoch
            t.start()

    # -- consumer --------------------------------------------------------
    def next(self) -> PreppedBatch:
        if not self.prefetch:
            with self.source_lock:
                pb = self.prep_fn()
            pb.epoch = self._epoch
            return self._finish(pb)
        self._ensure_thread()
        while True:
            try:
                kind, epoch, item = self._q.get(timeout=1.0)
            except queue.Empty:
                if not self._thread.is_alive() and self._q.empty():
                    raise IngestThreadDied(
                        "ingest prefetch thread died without delivering "
                        "a batch or an error"
                    )
                continue
            if epoch != self._epoch:
                continue     # pre-restore batch: dropped, source rewound
            if kind == "err":
                raise item
            return item

    def try_next(self) -> Optional[PreppedBatch]:
        """Non-blocking ``next()`` for the resident drain's greedy ring
        fill: a ready batch, or None when the queue is empty RIGHT NOW
        (the caller dispatches what it already holds instead of
        waiting). Inline (prefetch-off) pipelines always return None —
        there is no queue to be ahead in, and polling the source here
        would turn the greedy accumulate into an unbounded synchronous
        poll loop. A dead producer also returns None: the next blocking
        ``next()`` surfaces IngestThreadDied with its full context."""
        if not self.prefetch:
            return None
        self._ensure_thread()
        while True:
            try:
                kind, epoch, item = self._q.get_nowait()
            except queue.Empty:
                return None
            if epoch != self._epoch:
                continue     # pre-restore batch: dropped, source rewound
            if kind == "err":
                raise item
            return item

    def mark_applied(self, pb: PreppedBatch):
        """Record pb's offsets as the applied cut — everything up to and
        including this batch has been dispatched to device state, so a
        snapshot taken from here restores without skipping or
        double-applying records."""
        self._applied = pb.offsets

    def applied_offsets(self):
        return self._applied

    # -- restore protocol ------------------------------------------------
    def pause(self):
        """Park the producer; returns only when it is off the source (or
        was never started / prefetch is off)."""
        self._pause_req.set()
        self._gate.clear()
        if not self.prefetch or self._thread is None:
            return
        while self._thread.is_alive() and not self._parked.is_set():
            self._parked.wait(0.1)

    def resume(self, applied_offsets: Any):
        """Invalidate every batch prepped before the pause and restart
        production from the (rewound) source position. ``applied_offsets``
        re-arms the cut — it IS the restored snapshot's offsets."""
        self._epoch += 1
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._device_ring is not None:
            # the epoch bump above already invalidates every queued
            # batch referencing these slots; retiring them re-opens the
            # full ring to the post-restore epoch's producer
            self._device_ring.clear()
        self._applied = applied_offsets
        if self._thread is not None and self._thread.is_alive():
            # the surviving (parked) producer serves the new epoch from
            # here on — re-stamp it so a LATER hard death is surfaced as
            # IngestThreadDied rather than mistaken for a restore respawn
            # (a stale stamp would silently respawn past lost records);
            # a thread already dead here keeps its old stamp so
            # _ensure_thread treats the post-restore spawn as legitimate
            self._thread_epoch = self._epoch
        self._pause_req.clear()
        self._gate.set()

    def close(self):
        self._stop.set()
        self._gate.set()
