"""LocalExecutor: drives a job's micro-batch loop on the device mesh.

The role of StreamTask.invoke + StreamInputProcessor.processInput
(SURVEY §3.2) collapsed into a host loop around ONE compiled SPMD step per
keyed stage:

    poll source -> host chain (fused stateless ops) -> key/encode ->
    device step(state, batch, watermark) -> decode fires -> sinks

Checkpoint barriers are step boundaries (no BarrierBuffer needed: between
steps, device state + source offsets form a consistent cut — the
Chandy-Lamport cut is structural).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import pickle
import queue
import sys
import threading
import time
from collections import deque, namedtuple
from functools import partial
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core.time import TimeDomain
from flink_tpu.core.types import KeyCodec
from flink_tpu.graph import stream_graph as sg
from flink_tpu.ops import window_kernels as wk
from flink_tpu.parallel.exchange import bucket_capacity
from flink_tpu.parallel.mesh import MeshContext
from flink_tpu.checkpointing import changelog as cklog
from flink_tpu.checkpointing import manifest as ckmf
from flink_tpu.checkpointing.materializer import (
    Materializer,
    MaterializerError,
)
from flink_tpu.checkpointing.local import local_cache_from_config
from flink_tpu.checkpointing.policy import (
    CheckpointFailureBudgetExceeded,
    policy_from_config,
)
from flink_tpu.metrics.drain_stats import DrainTelemetry
from flink_tpu.metrics.recovery import RecoveryTracker
from flink_tpu.metrics.tracing import (
    CompileEvents,
    cost_analysis_of,
    tracer_from_config,
)
from flink_tpu.runtime import controller as controller_mod
from flink_tpu.runtime import elastic
from flink_tpu.runtime import ingest as ingest_mod
from flink_tpu.runtime import stages as stages_mod
from flink_tpu.runtime.step import (
    WindowStageSpec,
    build_compact_step,
    build_kg_occupancy_step,
    build_window_chained_drain,
    build_window_chained_drain_sharded,
    build_window_fire_reduced_step,
    build_window_fire_step,
    build_window_megastep,
    build_window_megastep_exchange,
    build_window_megastep_fired,
    build_window_megastep_fired_exchange,
    build_window_resident_drain,
    build_window_resident_drain_exchange,
    build_window_sharded_drain,
    build_window_while_drain,
    build_window_while_drain_sharded,
    build_window_update_step,
    build_window_update_step_exchange,
    clear_dirty,
    clear_overflow,
    init_sharded_state,
)
from flink_tpu.runtime import checkpoint as ckpt
from flink_tpu.runtime import tiers as tiers_mod
from flink_tpu.runtime.cluster import JobCancelledException
from flink_tpu.runtime.union import to_elements
from flink_tpu.runtime.watchdog import WatchdogError, watchdog_from_config
from flink_tpu.runtime.watermarks import WatermarkStrategy
from flink_tpu.testing import faults

WindowResult = namedtuple("WindowResult", ["key", "window_end_ms", "value"])
SessionResult = namedtuple(
    "SessionResult", ["key", "window_start_ms", "window_end_ms", "value"]
)


class _LaggedEmitter:
    """Pipelined emission for per-step output handles: reading a step's
    outputs immediately blocks on it (a cold d2h costs ~70ms fixed on
    this runtime), so up to ``lag`` steps' handles are retained and read
    only when they fall off the window — the read then overlaps the
    subsequent dispatches. FIFO order is preserved; ``idle()`` drains
    everything the moment the source has nothing new (computed results
    must never be withheld behind an idle stream); ``lag == 0`` is fully
    synchronous (the pre-pipelining behavior). Shared by the rolling and
    session runners."""

    CONFIG_KEY = "pipeline.max-inflight-steps"

    def __init__(self, env, emit_fn):
        self.lag = max(0, env.config.get_int(self.CONFIG_KEY, 4))
        self.emit_fn = emit_fn
        self._q = deque()

    def push(self, item):
        self._q.append(item)
        while len(self._q) > self.lag:
            self.emit_fn(self._q.popleft())

    def idle(self):
        self.drain()

    def drain(self):
        while self._q:
            self.emit_fn(self._q.popleft())

    def discard(self):
        """Drop retained handles WITHOUT emitting — restore rewinds the
        sink to the checkpoint cut, and replay re-fires everything after
        it; emitting the stale handles would double-count."""
        self._q.clear()


def classify_failure(exc: BaseException) -> str:
    """Failure classification at the restart boundary (ref the
    coarse-grained recovery split in RestartPipelinedRegionFailover-
    Strategy — here the regions are "the host-side plumbing" vs "the
    state itself"). TRANSIENT host-side failures — a watchdog trip, an
    exhausted checkpoint failure budget, a DCN peer stall/loss, the
    ingest thread dying, a connection/timeout blip — say nothing about
    the integrity of the live device state or the compiled kernels, so
    recovery may restart warm in-process: keep the jitted steps, re-stage
    only what diverged from the restored cut. DEVICE LOSS (a mesh
    shard's chip gone — runtime/elastic.py) is its own kind: the
    checkpoint is fine but the mesh is wrong, so recovery re-plans the
    job over the survivors instead of restoring onto a dead device.
    Anything else (arithmetic/assertion/XLA errors, unknown exceptions)
    is treated as STATE-CORRUPTING and takes the full restore path,
    rebuilding every shard from the checkpoint."""
    from flink_tpu.runtime import dcn

    if isinstance(exc, elastic.DeviceLostError):
        # checked FIRST: DCNPeerLostError is both a DCNPeerError (in
        # the transient tuple) and a DeviceLostError — the dead peer's
        # mesh segment is gone, which no warm restart survives
        return "device-loss"
    transient = (
        WatchdogError,
        CheckpointFailureBudgetExceeded,
        MaterializerError,
        ingest_mod.IngestThreadDied,
        dcn.DCNPeerError,
        ConnectionError,
        TimeoutError,
    )
    return "transient" if isinstance(exc, transient) else "state-corrupting"


def _storage_for_restore_path(live_storage, path_or_storage):
    """Resolve a restore target: an own-directory path rides the live
    storage object (and its task-local snapshot cache); a foreign path
    gets a plain reader; a storage object passes through."""
    if not isinstance(path_or_storage, str):
        return path_or_storage
    if live_storage is not None and os.path.abspath(
        path_or_storage
    ) == os.path.abspath(live_storage.dir):
        return live_storage
    return ckpt.CheckpointStorage(path_or_storage)


def _pad(arr, size, dtype):
    arr = np.asarray(arr, dtype)
    if len(arr) == size:
        return arr
    out = np.zeros((size,) + arr.shape[1:], dtype)
    out[: len(arr)] = arr
    return out


class _GenericCheckpointIO:
    """Async write machinery shared by every generic (pickled-payload)
    checkpoint path — flat-stage, keyed-process, and device-CEP. Owns
    the optional Materializer, the completion-notification queue, and
    the drain/flush/recover/close protocol, so the three paths cannot
    diverge. (The windowed path has its own staged delta pipeline.)

    checkpoint.async defaults on when checkpoint.mode=incremental —
    the same rule as the windowed path, so /checkpoints/config reports
    what actually runs. The generic payloads themselves are always full
    snapshots (one small pytree/dict — nothing to delta)."""

    def __init__(self, env, storage, pipe, policy=None):
        self.storage = storage
        self.pipe = pipe
        # optional CheckpointFailurePolicy: completions reset its
        # consecutive-failure count AT PUBLISH TIME (sync inline, async
        # on the materializer thread — the policy is thread-safe)
        self.policy = policy
        # serializes source wire interactions against a pipelined-ingest
        # producer (runtime/ingest.py): the windowed runner points this
        # at its pipeline's source_lock — an offset commit may share the
        # poll's connection, and an interleaved commit mid-fetch would
        # corrupt the protocol. Runners that poll inline have no
        # concurrent producer, so the no-op default costs nothing.
        self.source_lock = contextlib.nullcontext()
        self.materializer = None
        if storage is not None and env.config.get_bool(
            "checkpoint.async",
            env.config.get_str("checkpoint.mode", "full") == "incremental",
        ):
            self.materializer = Materializer(
                slots=env.config.get_int("checkpoint.staging-slots", 2)
            )
        # (cid, offsets) of durable checkpoints awaiting completion
        # fan-out: the materializer thread only QUEUES here — the step
        # loop delivers, because notify_checkpoint_complete mutates
        # connector state the hot path touches concurrently
        self._notify_q = deque()

    def queue_notification(self, cid, offsets):
        """Record a now-durable checkpoint for fan-out at the next
        drain. Called from the materializer thread by write paths that
        serialize their own files (the windowed staged-delta pipeline)."""
        self._notify_q.append((cid, offsets))

    def drain(self):
        """Deliver queued checkpoint-complete fan-outs ON THIS (the
        step loop's) thread."""
        while self._notify_q:
            cid, offsets = self._notify_q.popleft()
            with self.source_lock:
                self.pipe.source.notify_checkpoint_complete(cid, offsets)
            for s in self.pipe.all_sinks:
                s.notify_checkpoint_complete(cid)

    def write(self, cid, payload):
        """Write a generic checkpoint + schedule its completion fan-out.
        Async mode pickles NOW (the live payload keeps mutating once the
        step loop resumes) and ships frozen bytes to the materializer."""
        self.drain()
        if self.materializer is None:
            self.storage.write_generic(cid, payload)
            if self.policy is not None:
                self.policy.on_completed(cid)
            with self.source_lock:
                self.pipe.source.notify_checkpoint_complete(
                    cid, payload["offsets"]
                )
            for s in self.pipe.all_sinks:
                s.notify_checkpoint_complete(cid)
            return
        self.materializer.check()
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        offsets = payload["offsets"]

        def task():
            self.storage.write_generic(cid, payload_bytes=blob)
            if self.policy is not None:
                self.policy.on_completed(cid)
            self._notify_q.append((cid, offsets))

        self.materializer.submit(f"chk-{cid}", task)

    def _drain_timeout(self):
        """Bound on recovery/teardown drains: a WEDGED write must not
        turn the escalation path into the very hang the containment
        layer exists to eliminate. checkpoint.timeout when configured;
        a generous fallback otherwise (0/unset timeout = the operator
        chose unbounded publishes, but recovery still terminates)."""
        t = getattr(self.policy, "timeout_s", 0) if self.policy else 0
        return t if t and t > 0 else 600.0

    def recover(self):
        """Restore-time drain: in-flight async writes land (each is a
        valid cut the restore may pick up), stored failures drop."""
        if self.materializer is not None:
            self.materializer.recover(timeout=self._drain_timeout())
            self.drain()

    def flush(self):
        """Success-path barrier: a still-failing async write IS a
        checkpoint failure — raises inside the caller's restart scope."""
        if self.materializer is not None:
            self.materializer.flush()
            self.drain()

    def settle(self):
        """Failure-path barrier: let pending cuts become durable before
        the caller checks whether a restartable checkpoint exists —
        bounded, so a wedged write cannot stall the restart decision."""
        if self.materializer is not None:
            self.materializer.flush(raise_errors=False,
                                    timeout=self._drain_timeout())

    def close(self):
        if self.materializer is not None:
            self.materializer.close(flush=True,
                                    timeout=self._drain_timeout())


def _guarded_generic_write(ck_io, policy, storage, metrics, cid,
                           payload_fn):
    """Abort-and-count containment for the generic checkpoint paths
    (docs/fault-tolerance.md): a failed attempt — including an async
    failure surfacing at this barrier via the materializer check — is
    GC'd and recorded, and the job keeps running until the consecutive-
    failure budget is exhausted. ``payload_fn`` builds the payload
    INSIDE the guard, so a snapshot-time failure is contained too."""
    t0 = time.perf_counter()
    trigger_ms = time.time() * 1000
    try:
        ck_io.write(cid, payload_fn())
    except (JobCancelledException, WatchdogError,
            CheckpointFailureBudgetExceeded):
        raise
    except Exception as e:
        storage.discard_tmp(cid)
        metrics.checkpoints_aborted += 1
        metrics.record_checkpoint_abort(
            cid, trigger_ms, (time.perf_counter() - t0) * 1e3,
            reason=f"{type(e).__name__}: {e}", kind="generic",
        )
        if policy.on_aborted(cid, str(e)):
            raise policy.exhausted_error(cid, e) from e


class _FlatStageCheckpointer:
    """Step-boundary checkpoint/savepoint/restore for keyed stage kinds
    whose device state is ONE flat pytree of per-shard arrays (rolling
    reduce, count windows). The reference snapshots EVERY operator's
    state (AbstractStreamOperator.java:367; rolling aggregates live in
    ValueState via StreamGroupedReduce), so these stage kinds must
    participate in the same fault-tolerance story as the windowed paths.

    Mirrors the session runner's inline machinery: a raw device_get of
    the state leaves at the step boundary (the structural barrier,
    SURVEY §3.4) + source offsets + sink states + the codec reverse map
    riding the append-only keymap log. Pending lagged fires are DRAINED
    before a cut (their sink effects belong to it) and DISCARDED on
    restore (source replay re-fires them). Stage-shape scalars that the
    compiled step bakes into its masks (capacity, count-window N, reduce
    kind) are validated at restore — mismatched arrays would corrupt
    silently via clamped gathers, so fail fast instead."""

    def __init__(self, executor, pipe, ctx, codec, keep_rev, emitter,
                 metrics, get_state, set_state, stage_kind, meta,
                 extra_payload=None, apply_extra=None):
        env = executor.env
        self.executor = executor
        self.env = env
        self.pipe = pipe
        self.ctx = ctx
        self.codec = codec
        self.keep_rev = keep_rev
        self.emitter = emitter
        self.metrics = metrics
        self.get_state = get_state
        self.set_state = set_state
        self.stage_kind = stage_kind
        self.meta = dict(meta)
        # stage-specific non-array state riding the payload (e.g. the
        # session path's watermark + time-domain origin)
        self.extra_payload = extra_payload
        self.apply_extra = apply_extra
        self.storage = None
        if env.checkpoint_dir:
            self.storage = ckpt.CheckpointStorage(
                env.checkpoint_dir,
                retain=env.config.get_int("checkpoint.retain", 2),
                local=local_cache_from_config(
                    env.config, env.checkpoint_dir
                ),
            )
        self.next_cid = (
            (self.storage.latest() or 0) + 1 if self.storage else 1
        )
        # failure budget (checkpointing/policy.py): generic stages get
        # the same abort-and-count containment as the windowed path
        self.policy = (
            policy_from_config(env.config)
            if self.storage is not None else None
        )
        # the live policy object: the web monitor snapshots .state()
        metrics.failure_budget = self.policy
        self._pause_declined = False
        self.io = _GenericCheckpointIO(
            env, self.storage, pipe, policy=self.policy
        )
        self.steps_at_ckpt = 0
        self.n_keys_logged = 0
        executor._savepoint_writer = self.write_savepoint

    def _payload(self, store):
        # codec reverse map rides the APPEND-ONLY keymap log: each
        # checkpoint writes only the keys seen since the last one
        if self.keep_rev:
            items, self.n_keys_logged = self.codec.rev_slice(
                self.n_keys_logged
            )
            store.append_keymap(items)
        leaves, _ = jax.tree_util.tree_flatten(self.get_state())
        return {
            "stage_state": [np.asarray(jax.device_get(x)) for x in leaves],
            "offsets": self.pipe.source.snapshot_offsets(),
            "codec_rev_count": self.n_keys_logged if self.keep_rev else 0,
            "sink_states": [
                s.snapshot_state() for s in self.pipe.all_sinks
            ],
            "max_parallelism": self.env.max_parallelism,
            "n_shards": self.ctx.n_shards,
            "stage_kind": self.stage_kind,
            "stage_meta": dict(self.meta),
            "stage_extra": (
                self.extra_payload() if self.extra_payload else {}
            ),
        }

    def maybe_checkpoint(self):
        self.io.drain()
        if (
            self.storage is not None
            and self.env.checkpoint_interval_steps > 0
            and self.metrics.steps - self.steps_at_ckpt
            >= self.env.checkpoint_interval_steps
        ):
            # min-pause gate (checkpoint.min-pause): a due trigger
            # defers until the pause elapses; ONE decline is counted per
            # deferred trigger, not one per polled cycle
            if self.policy is not None and not self.policy.can_trigger():
                if not self._pause_declined:
                    self._pause_declined = True
                    self.metrics.checkpoints_declined += 1
                return
            self._pause_declined = False
            self.write_checkpoint()

    def write_checkpoint(self):
        self.emitter.drain()
        _guarded_generic_write(
            self.io, self.policy, self.storage, self.metrics,
            self.next_cid, lambda: self._payload(self.storage),
        )
        self.next_cid += 1
        self.steps_at_ckpt = self.metrics.steps

    def restore(self, path_or_storage, cid=None):
        self.io.recover()             # durable cuts still notify
        st = _storage_for_restore_path(self.storage, path_or_storage)
        cid = cid if cid is not None else st.latest()
        if cid is None:
            raise FileNotFoundError(f"no checkpoint in {st.dir}")
        payload = st.read_generic(cid)
        if payload.get("session_window") and "stage_kind" not in payload:
            # round-4 inline session format: adapt to the unified shape
            # so retained checkpoints/savepoints stay restorable
            payload = {
                **payload,
                "stage_kind": "session-window",
                "stage_state": payload["session_state"],
                "stage_meta": {
                    "gap_ms": payload["gap_ms"],
                    "capacity_per_shard": payload["capacity_per_shard"],
                },
                "stage_extra": {
                    "wm_current": payload["wm_current"],
                    "origin_ms": payload["origin_ms"],
                },
            }
        if payload.get("stage_kind") != self.stage_kind:
            raise ValueError(
                f"checkpoint was not written by a {self.stage_kind} "
                f"stage (found {payload.get('stage_kind')!r})"
            )
        if payload["max_parallelism"] != self.env.max_parallelism:
            raise ValueError("checkpoint max-parallelism mismatch")
        if payload["n_shards"] != self.ctx.n_shards:
            raise ValueError(
                f"checkpoint has {payload['n_shards']} shard(s), job "
                f"configured for {self.ctx.n_shards}"
            )
        snap_meta = payload.get("stage_meta", {})
        for k, v in self.meta.items():
            if snap_meta.get(k) != v:
                raise ValueError(
                    f"checkpoint {k} {snap_meta.get(k)!r} != "
                    f"configured {v!r}"
                )
        self.emitter.discard()
        _leaves, treedef = jax.tree_util.tree_flatten(self.get_state())
        self.set_state(jax.tree_util.tree_unflatten(treedef, [
            jax.device_put(x, self.ctx.state_sharding)
            for x in payload["stage_state"]
        ]))
        self.pipe.source.restore_offsets(payload["offsets"])
        sink_states = payload.get("sink_states")
        if sink_states:
            if len(sink_states) != len(self.pipe.all_sinks):
                raise ValueError(
                    f"checkpoint has {len(sink_states)} sink states "
                    f"but the job topology has {len(self.pipe.all_sinks)} "
                    f"sinks — restore with the matching pipeline"
                )
            for s, ss in zip(self.pipe.all_sinks, sink_states):
                s.restore_state(ss)
        count = payload.get("codec_rev_count", 0)
        if self.keep_rev and count:
            self.codec._rev = st.read_keymap(count)
            # restoring from a FOREIGN directory (savepoint): the job's
            # own keymap log has none of these keys, so the next
            # checkpoint must append them all (n_keys_logged=0);
            # same-dir restores resume the append-only log where it is
            same_dir = self.storage is not None and (
                os.path.abspath(st.dir)
                == os.path.abspath(self.storage.dir)
            )
            self.n_keys_logged = len(self.codec._rev) if same_dir else 0
        if self.apply_extra is not None:
            self.apply_extra(payload.get("stage_extra", {}))
        self.steps_at_ckpt = self.metrics.steps

    def write_savepoint(self, path: str) -> str:
        self.emitter.drain()
        sp = ckpt.CheckpointStorage(path, retain=10**9)
        cid = (sp.latest() or 0) + 1
        # self-contained savepoint: full keymap into ITS directory
        logged = self.n_keys_logged
        self.n_keys_logged = 0
        try:
            return sp.write_generic(cid, self._payload(sp))
        finally:
            self.n_keys_logged = logged

    def run_with_restarts(self, batch_loop, restore_from):
        """Restore + restart protection around the stage's batch loop
        (ref ExecutionGraph.restart)."""
        if restore_from:
            self.restore(restore_from)
        restart = self.executor._restart_strategy()
        try:
            while True:
                try:
                    batch_loop()
                    self.io.flush()
                    break
                except JobCancelledException:
                    raise
                except Exception:
                    self.io.settle()
                    can = (
                        self.storage is not None
                        and self.storage.latest() is not None
                        and restart.should_restart()
                    )
                    if not can:
                        raise
                    self.metrics.restarts += 1
                    self.executor._notify_restart()
                    self.restore(self.storage)
        finally:
            self.io.close()


@dataclasses.dataclass
class JobMetrics:
    records_in: int = 0
    records_out: int = 0
    fires: int = 0
    steps: int = 0
    steps_fast: int = 0   # steps run on the lookup-only fast tier
    steps_exchanged: int = 0  # steps routed through the ICI all_to_all
    # steps drained through the shard_map'd data-parallel ring
    # (pipeline.data-parallel): records pre-routed to the owning
    # shard's slice, zero collectives in the keyed body
    steps_sharded: int = 0
    # K-fused lax.scan dispatches (pipeline.steps-per-dispatch > 1);
    # each one carries k_steps micro-batches of the `steps` counter
    fused_dispatches: int = 0
    # ...of which resident-pipeline dispatches (pipeline.fused-fire):
    # the fire sweep ran inside the scan and payloads surfaced lagged
    fused_fire_dispatches: int = 0
    # device-resident ring-drain dispatches (pipeline.resident-loop);
    # each carries up to ring-depth micro-batches of `steps` in ONE
    # count-gated scan — THE steady-state host-round-trip divisor
    resident_drains: int = 0
    state_layout: str = ""  # "hash" | "direct" once the stage is set up
    # packed acc+touched planes in effect (state.packed-planes)
    state_packed_planes: bool = False
    # "mask" | "all_to_all" | "adaptive" once the stage is set up
    exchange_mode: str = ""
    dropped_late: int = 0
    dropped_capacity: int = 0
    restarts: int = 0
    # failure containment (docs/fault-tolerance.md): aborted-and-counted
    # checkpoints, min-pause trigger declines, watchdog deadline trips
    checkpoints_aborted: int = 0
    checkpoints_declined: int = 0
    watchdog_trips: int = 0
    # the live CheckpointFailurePolicy (checkpointing/policy.py); the
    # web monitor serves its .state() snapshot on
    # /jobs/<jid>/checkpoints. None when checkpointing is off.
    failure_budget: Any = None
    # DCN path: records THIS host's lanes carried (post ingest
    # partitioning — shows rebalance/shuffle/global routing physically)
    dcn_ingested_local: int = 0
    wall_time_s: float = 0.0
    # CEP: which engine actually ran ("device" | "host"; VERDICT r3 —
    # a user must be able to tell without diffing step counters), plus
    # device count-NFA detections vs host-replay extractions — the
    # two must agree (honesty cross-check for the accelerated path)
    cep_engine: str = ""
    cep_device_steps: int = 0
    cep_matches_detected: int = 0
    cep_matches_extracted: int = 0
    # fire latency: bounded weighted samples — latency is watermark-
    # crossing -> sink invoke for every window in one emission
    # (ref LatencyMarker / the p99 half of the north-star metric)
    fire_latency: Any = None
    # checkpoint history (ref CheckpointStatsTracker): bounded list of
    # {"id", "trigger_ms", "duration_ms", "bytes", "entries"} dicts,
    # newest last — served by the web monitor's /checkpoints handler
    checkpoint_stats: Any = None

    def record_checkpoint(self, cid: int, trigger_ms: float,
                          duration_ms: float, nbytes: int, entries: int,
                          kind: str = "full", sync_ms: float = None,
                          async_ms: float = None, coverage: int = None,
                          staging_wait_ms: float = 0.0,
                          staging_occupancy: int = 0):
        """kind: "full" | "delta". sync_ms is the step-loop stall (drain +
        staging fetch + offset capture + staging-slot wait); async_ms the
        background materialization (extract/serialize/publish). Sync-mode
        checkpoints report the whole duration as sync_ms."""
        if self.checkpoint_stats is None:
            self.checkpoint_stats = []
        row = {
            "id": cid,
            "status": "completed",
            "trigger_ms": round(trigger_ms, 1),
            "duration_ms": round(duration_ms, 2),
            "bytes": nbytes,
            "entries": entries,
            "kind": kind,
            "sync_ms": round(
                duration_ms if sync_ms is None else sync_ms, 2
            ),
            "async_ms": round(async_ms or 0.0, 2),
            "staging_wait_ms": round(staging_wait_ms, 2),
            "staging_occupancy": staging_occupancy,
        }
        if coverage is not None:
            row["coverage"] = coverage
        self.checkpoint_stats.append(row)
        del self.checkpoint_stats[:-200]      # bounded history

    def record_checkpoint_abort(self, cid: int, trigger_ms: float,
                                duration_ms: float, reason: str,
                                kind: str = "full"):
        """An aborted-and-counted checkpoint (failure-budget path): the
        attempt rides the same history the web monitor serves, with
        status "aborted" and the failure reason, so an operator sees the
        contained fault instead of a silent gap in the ids."""
        if self.checkpoint_stats is None:
            self.checkpoint_stats = []
        self.checkpoint_stats.append({
            "id": cid,
            "status": "aborted",
            "trigger_ms": round(trigger_ms, 1),
            "duration_ms": round(duration_ms, 2),
            "bytes": 0,
            "entries": 0,
            "kind": kind,
            "sync_ms": 0.0,
            "async_ms": 0.0,
            "staging_wait_ms": 0.0,
            "staging_occupancy": 0,
            "failure_reason": reason[:500],
        })
        del self.checkpoint_stats[:-200]

    def record_fire_latency(self, n_windows: int, ms: float):
        from flink_tpu.metrics.latency import LatencySamples

        if self.fire_latency is None:
            self.fire_latency = LatencySamples()
        self.fire_latency.record(n_windows, ms)

    def fire_latency_pct(self, q: float):
        """Weighted percentile (0..100) over emitted windows; None if none."""
        if not self.fire_latency:
            return None
        return self.fire_latency.percentile(q)

    # the counter fields exported as live gauges (also consumed by the
    # MiniCluster's job detail endpoint)
    GAUGE_FIELDS = (
        "records_in", "records_out", "fires", "steps", "steps_fast",
        "steps_sharded",
        "fused_dispatches", "fused_fire_dispatches", "resident_drains",
        "dropped_late", "dropped_capacity", "restarts",
        "checkpoints_aborted", "checkpoints_declined", "watchdog_trips",
    )


@dataclasses.dataclass
class JobHandle:
    name: str
    metrics: JobMetrics
    state: Any = None      # final device state (windowed stages)
    ctx: Any = None
    # merged accumulator values (ref JobExecutionResult.getAllAccumulator-
    # Results); empty when no rich function registered any
    accumulator_results: Any = None

    def accumulator_result(self, name: str):
        return (self.accumulator_results or {})[name]


@dataclasses.dataclass
class _Pipeline:
    source: Any
    pre_chain: List[sg.OneInputTransformation]
    ts_transform: Optional[sg.TimestampsWatermarksTransformation]
    key_by: Optional[sg.KeyByTransformation]
    window_agg: Optional[sg.WindowAggTransformation]
    rolling: Optional[sg.KeyedProcessTransformation]
    # post-stage fan-out: each branch is (chain_ops, [sinks]); divergent
    # sink lineages after the last stateful stage become separate branches
    # (the role of the reference's Output broadcasting to multiple edges)
    branches: List[Any]
    process: Optional[sg.ProcessTransformation] = None
    # explicit exchange annotation upstream of key_by (rebalance /
    # shuffle / global / rescale / forward); physical on the DCN path's
    # ingestion edge, a recorded no-op single-host (see
    # PartitionTransformation)
    ingest_partition: Optional[str] = None
    # downstream keyed windowed stages beyond (key_by, window_agg):
    # ordered [key_by, window_agg] pairs collected by _translate, turned
    # into a validated StageGraph (runtime/stages.py) at dispatch
    stages: List[Any] = dataclasses.field(default_factory=list)

    @property
    def all_sinks(self):
        return [s for _, sinks in self.branches for s in sinks]


def _emit_batch(pipe: _Pipeline, elements, metrics: JobMetrics) -> int:
    """Run each post-stage branch chain over `elements` and invoke sinks."""
    total = 0
    for chain, sinks in pipe.branches:
        out = _apply_chain(chain, elements) if chain else elements
        total += len(out)
        for s in sinks:
            s.invoke_batch(out)
    metrics.records_out += total
    return total


def _translate_branch(parent: sg.Transformation):
    """Translate one union input into (source, pre_ts_ops, ts, post_ts_ops).

    Ops are split around the timestamp assigner so the timestamp_fn sees the
    element exactly as it was at the assigner's position in the chain."""
    pre_ops, post_ops, source, ts = [], [], None, None
    for t in sg.lineage(parent):
        if isinstance(t, sg.SourceTransformation):
            source = t.source
        elif isinstance(t, sg.TimestampsWatermarksTransformation):
            ts = t
        elif isinstance(t, sg.OneInputTransformation):
            (post_ops if ts is not None else pre_ops).append(t)
        elif isinstance(t, sg.PartitionTransformation):
            pass
        else:
            raise NotImplementedError(
                f"{type(t).__name__} upstream of a union/connect is not "
                f"supported yet (only source -> stateless chain)"
            )
    if source is None:
        raise ValueError("union input has no source")
    return source, pre_ops, ts, post_ops


def _merge_sources(u: sg.UnionTransformation):
    """Build a MergedSource + synthesized ts transform for a union head."""
    from flink_tpu.runtime import union as un

    branches, have_ts = [], []
    for i, parent in enumerate(u.parents):
        source, pre_ops, ts, post_ops = _translate_branch(parent)
        branches.append(un.Branch(
            source, pre_ops,
            ts_fn=ts.timestamp_fn if ts is not None else None,
            post_ops=post_ops,
            strategy=ts.strategy if ts is not None else None,
            tag=i if u.tagged else None,
        ))
        have_ts.append(ts is not None)
    merged = un.MergedSource(branches)
    ts_transform = None
    if any(have_ts):
        if not u.tagged:
            raise NotImplementedError(
                "assign timestamps AFTER union() (per-input assigners need "
                "the tagged connect/join path)"
            )
        if not all(have_ts):
            raise ValueError(
                "either all or none of the connected/joined inputs must "
                "assign timestamps"
            )
        strategy = un.MergedWatermarkStrategy(
            out_of_orderness_ms=max(
                b.strategy.out_of_orderness_ms for b in branches
            ),
            branches=branches,
        )
        ts_transform = sg.TimestampsWatermarksTransformation(
            "merged-ts", None,
            timestamp_fn=lambda e: e.ts,
            strategy=strategy,
        )
    return merged, ts_transform


def _translate(sink_transforms: List[sg.SinkTransformation]) -> _Pipeline:
    if not sink_transforms:
        raise ValueError("job has no sinks")
    spines, tails = [], []
    for st in sink_transforms:
        body = sg.lineage(st)[:-1]
        i = len(body)
        while i > 0 and isinstance(
            body[i - 1],
            (sg.OneInputTransformation, sg.PartitionTransformation),
        ):
            i -= 1
        spines.append(body[:i])
        tails.append(body[i:])
    # stateless jobs have an empty spine except the source; normalize so the
    # source is always on the spine
    ref = spines[0]
    for sp in spines[1:]:
        if [t.id for t in sp] != [t.id for t in ref]:
            raise NotImplementedError(
                "sinks must share the pipeline up to the last stateful "
                "stage; divergence is supported only in trailing "
                "stateless chains"
            )
    # group identical tails into branches
    branches, by_key = [], {}
    for tail, st in zip(tails, sink_transforms):
        key = tuple(t.id for t in tail)
        if key not in by_key:
            entry = (
                [t for t in tail if isinstance(t, sg.OneInputTransformation)],
                [],
            )
            by_key[key] = entry
            branches.append(entry)
        by_key[key][1].append(st.sink)

    pipe = _Pipeline(None, [], None, None, None, None, branches)
    for t in ref:
        if isinstance(t, sg.SourceTransformation):
            pipe.source = t.source
        elif isinstance(t, sg.UnionTransformation):
            pipe.source, pipe.ts_transform = _merge_sources(t)
        elif isinstance(t, sg.IterateTransformation):
            from flink_tpu.runtime.union import IterationSource

            pipe.source = IterationSource(
                pipe.source, pipe.pre_chain, t.queue
            )
            pipe.pre_chain = []
        elif isinstance(t, sg.TimestampsWatermarksTransformation):
            pipe.ts_transform = t
        elif isinstance(t, sg.KeyByTransformation):
            if pipe.key_by is None:
                pipe.key_by = t
            else:
                # a SECOND keyed boundary: collect it for the StageGraph
                # (runtime/stages.py) instead of silently overwriting the
                # first — the chain validates at dispatch, where every
                # unsupported shape raises naming its edge
                pipe.stages.append([t, None])
        elif isinstance(t, sg.WindowAggTransformation):
            if pipe.stages:
                if pipe.stages[-1][1] is not None:
                    from flink_tpu.runtime.stages import StageGraphError

                    raise StageGraphError(
                        f"two window aggregations with no keyBy between "
                        f"them after stage[{len(pipe.stages)}] — every "
                        f"chained stage is a keyBy→window pair"
                    )
                pipe.stages[-1][1] = t
            elif pipe.window_agg is not None:
                from flink_tpu.runtime.stages import StageGraphError

                raise StageGraphError(
                    "two window aggregations with no keyBy between them "
                    "— a downstream window must re-key the upstream "
                    "stage's results (.key_by(lambda r: r.key))"
                )
            else:
                pipe.window_agg = t
        elif isinstance(t, sg.KeyedProcessTransformation):
            pipe.rolling = t
        elif isinstance(t, sg.ProcessTransformation):
            pipe.process = t
        elif isinstance(t, sg.OneInputTransformation):
            pipe.pre_chain.append(t)
        elif isinstance(t, sg.PartitionTransformation):
            if t.mode not in ("broadcast", "forward"):
                pipe.ingest_partition = t.mode
        else:
            raise NotImplementedError(f"transformation {type(t).__name__}")
    if pipe.source is None:
        raise ValueError("pipeline has no source")
    if (pipe.key_by is not None and pipe.window_agg is None
            and pipe.rolling is None and pipe.process is None):
        raise NotImplementedError(
            "keyed stream must currently end in a window agg, rolling "
            "reduce, or process function"
        )
    if pipe.stages and (
        pipe.stages[-1][1] is None
        or pipe.rolling is not None or pipe.process is not None
    ):
        from flink_tpu.runtime.stages import StageGraphError

        raise StageGraphError(
            f"stage[{len(pipe.stages)}] does not end in a window "
            f"aggregation — a chained keyed stage must be a keyBy→window "
            f"pair (rolling reduces and process functions cannot chain "
            f"after a windowed stage)"
        )
    return pipe


def _apply_chain(chain, elements):
    for t in chain:
        if t.kind == "map":
            elements = [t.fn(e) for e in elements]
        elif t.kind == "filter":
            elements = [e for e in elements if t.fn(e)]
        elif t.kind == "flat_map":
            out = []
            for e in elements:
                out.extend(t.fn(e))
            elements = out
        else:
            raise NotImplementedError(t.kind)
    return elements


# builtin reduce kinds the spill tier can merge host-side:
# kind -> (accumulating numpy ufunc, neutral element)
_HOST_REDUCE = {
    "sum": (np.add, 0.0),
    "count": (np.add, 0.0),
    "min": (np.minimum, np.inf),
    "max": (np.maximum, -np.inf),
}


class CycleAttribution:
    """Per-cycle phase timing + back-pressure cause classification.

    The reference samples task-thread stack traces and classifies threads
    blocked on network buffers (BackPressureStatsTracker.java:64); in the
    micro-batch design each cycle decomposes exactly into phases, so the
    cause is measured, not sampled:

      source   — waiting on / reading the source
      host     — encode, key hashing, host chains
      dispatch — queueing device steps; BLOCKS when the device pipeline is
                 full (donated buffers unavailable) => device-bound
      emit     — fire readback + sink invocation => sink-bound

    Cycles with no records are source-starved. EWMAs + per-phase
    histograms feed /jobs/<jid>/backpressure.

    Resident-loop regimes (ISSUE 14): host-dispatch phases cannot see
    inside the ring drain, so when the drain flight recorder is live the
    executor plugs its duty-cycle estimator in as ``resident_fn`` and
    classification consults it FIRST — ``ring-starved`` (drains keep
    finding empty rings: publish side can't feed the device) and
    ``device-saturated`` (drains keep retiring full-depth ring groups:
    the device is the bottleneck) are more specific verdicts than the
    phase dominance rules below them.
    """

    PHASES = ("source", "host", "dispatch", "emit")
    RING_STARVED_ABOVE = 0.5      # mean empty-ring drain fraction
    DEVICE_SATURATED_ABOVE = 0.85  # mean drain duty cycle (count/depth)

    def __init__(self, group=None, alpha: float = 0.05):
        self.alpha = alpha
        self.ewma = {p: 0.0 for p in self.PHASES}
        self.idle = 0
        self.busy = 0
        # decaying idle fraction: classification must reflect the RECENT
        # regime, not the job's lifetime (a job idle overnight then
        # saturated must flip to device-bound, not stay source-starved)
        self.idle_ewma = 0.0
        # () -> (duty, starved) from metrics.drain_stats.DrainTelemetry
        # .regime(); None outside the resident loop
        self.resident_fn = None
        self.hists = (
            {p: group.histogram(f"phase_{p}_ms") for p in self.PHASES}
            if group is not None else None
        )

    def record(self, idle: bool, **phase_ms):
        self.idle_ewma += self.alpha * ((1.0 if idle else 0.0) - self.idle_ewma)
        if idle:
            self.idle += 1
            return
        self.busy += 1
        for p in self.PHASES:
            ms = phase_ms.get(p, 0.0)
            self.ewma[p] += self.alpha * (ms - self.ewma[p])
            if self.hists:
                self.hists[p].update(ms)

    def classify(self) -> str:
        total = self.idle + self.busy
        if total == 0:
            return "ok"
        if self.resident_fn is not None:
            duty, starved = self.resident_fn()
            if starved > self.RING_STARVED_ABOVE:
                return "ring-starved"
            if duty > self.DEVICE_SATURATED_ABOVE:
                return "device-saturated"
        if self.idle_ewma > 0.5:
            return "source-starved"
        dominant = max(self.ewma, key=self.ewma.get)
        cycle = sum(self.ewma.values()) or 1e-9
        if self.ewma[dominant] / cycle < 0.5:
            return "ok"
        return {
            "source": "source-starved",
            "host": "host-bound",
            "dispatch": "device-bound",
            "emit": "sink-bound",
        }[dominant]

    def report(self) -> dict:
        out = {
            "classification": self.classify(),
            "phase-ewma-ms": {p: round(v, 3) for p, v in self.ewma.items()},
            "idle-cycles": self.idle,
            "busy-cycles": self.busy,
        }
        if self.resident_fn is not None:
            duty, starved = self.resident_fn()
            out["drain-duty-cycle"] = round(duty, 4)
            out["ring-starved-fraction"] = round(starved, 4)
        return out


class LocalExecutor:
    def __init__(self, env):
        self.env = env
        # set per-stage once a snapshotting path exists (savepoint target)
        self._savepoint_writer = None
        self._job_group = None
        self._cycle_hist = None
        self._last_cycle_t = None
        self._attribution = None
        self._latency_hist = None
        # step-loop span tracer (metrics/tracing.py); None unless
        # observability.tracing is on — the off path carries no tracer
        self._tracer = None
        self._compile_sink = None

    def _poll_control(self):
        """Observe cancel/savepoint requests at the micro-batch boundary
        (the reference's Task cancellation + barrier injection cadence);
        also records the cycle-time histogram (back-pressure signal)."""
        if self._cycle_hist is not None:
            now = time.perf_counter()
            if self._last_cycle_t is not None:
                self._cycle_hist.update((now - self._last_cycle_t) * 1e3)
            self._last_cycle_t = now
        ctl = getattr(self.env, "_control", None)
        if ctl is None:
            return
        if ctl.cancel_event.is_set():
            req = ctl.take_savepoint_request()
            if req is not None:
                req.set_error(RuntimeError("job canceled"))
            raise JobCancelledException("job canceled")
        req = ctl.take_savepoint_request()
        if req is not None:
            if self._savepoint_writer is None:
                req.set_error(NotImplementedError(
                    "savepoints are not supported for this stage type"
                ))
            else:
                try:
                    req.set_result(self._savepoint_writer(req.path))
                except Exception as e:
                    req.set_error(e)

    def _init_metrics(self, job_name: str, metrics: JobMetrics):
        registry = getattr(self.env, "metric_registry", None)
        if registry is None:
            return
        grp = registry.group("jobs", job_name)
        self._job_group = grp
        for fname in JobMetrics.GAUGE_FIELDS:
            grp.gauge(fname, lambda m=metrics, n=fname: getattr(m, n))
        self._cycle_hist = grp.histogram("cycle_time_ms")
        self._attribution = CycleAttribution(grp)
        # LatencyMarker analog: ingest-to-sink latency of the youngest
        # records in each emission (markers are batch timestamps here)
        self._latency_hist = grp.histogram("record_latency_ms")
        self.env._backpressure_report = (
            lambda: self._attribution.report() if self._attribution else {}
        )
        # XLA compile visibility: process-global event counters snapshotted
        # at job start so the gauges report THIS job's compiles — a
        # recompile storm mid-stream moves a named metric instead of
        # presenting as a mystery stall (ISSUE 2 tentpole part 3)
        CompileEvents.install()
        mark = CompileEvents.mark()
        grp.gauge(
            "xla_compile_count", lambda: CompileEvents.since(mark)[0]
        )
        grp.gauge(
            "xla_compile_time_ms",
            lambda: round(CompileEvents.since(mark)[1] * 1e3, 2),
        )
        hist = grp.histogram("xla_compile_ms")
        self._compile_sink = CompileEvents.add_sink(
            lambda d, h=hist: h.update(d * 1e3)
        )
        self.env._compile_report = CompileEvents.report

    def _notify_restart(self):
        """ExecutionGraph hook: a restart creates new execution attempts
        (ref ExecutionGraph.restart). Called inside the restart `except`
        block, so the ACTIVE exception is the failure cause the attempt
        history records. Listener installed by MiniCluster."""
        listener = getattr(self.env, "_execution_listener", None)
        if listener is not None:
            exc = sys.exc_info()[1]
            cause = (
                f"{type(exc).__name__}: {exc}" if exc is not None
                else "restart"
            )
            try:
                listener("restart", cause)
            except Exception:
                pass      # observability must never kill the job

    def _restart_strategy(self) -> ckpt.RestartStrategy:
        """Reads go through the declared ConfigOptions so conf-file
        strings coerce strictly and parse errors name the key."""
        from flink_tpu.core.config import CoreOptions as CO

        cfg = self.env.config
        kind = cfg.get(CO.RESTART_STRATEGY)
        if kind == "fixed-delay":
            return ckpt.RestartStrategy.fixed_delay(
                cfg.get(CO.RESTART_ATTEMPTS),
                cfg.get(CO.RESTART_DELAY_S),
            )
        if kind == "failure-rate":
            return ckpt.RestartStrategy.failure_rate(
                cfg.get(CO.RESTART_FAILURE_RATE_MAX),
                cfg.get(CO.RESTART_FAILURE_RATE_INTERVAL),
                cfg.get(CO.RESTART_FAILURE_RATE_DELAY),
            )
        if kind == "exponential-backoff":
            return ckpt.RestartStrategy.exponential_backoff(
                cfg.get(CO.RESTART_EXP_INITIAL_DELAY),
                cfg.get(CO.RESTART_EXP_MAX_DELAY),
                cfg.get(CO.RESTART_EXP_MULTIPLIER),
                cfg.get(CO.RESTART_EXP_JITTER),
                cfg.get(CO.RESTART_EXP_RESET_AFTER),
            )
        if kind != "none":
            raise ValueError(
                f"restart-strategy must be none|fixed-delay|failure-rate|"
                f"exponential-backoff, got {kind!r}"
            )
        return ckpt.RestartStrategy.none()

    def run(self, job_name: str, sink_transforms, restore_from=None) -> JobHandle:
        from flink_tpu.core.time import TimeCharacteristic

        pipe = _translate(sink_transforms)
        metrics = JobMetrics()
        # live handle for web monitors (checkpoint stats are structured
        # history, not gauges — the registry only carries scalars)
        self.env._live_metrics = metrics
        self._init_metrics(job_name, metrics)
        # step-loop span tracing (observability.tracing; metrics/tracing):
        # attached to the env so /jobs/<jid>/traces can serve it live AND
        # after the job finishes
        self._tracer = tracer_from_config(
            getattr(self.env, "config", None), stage=job_name
        )
        self.env._span_tracer = self._tracer
        t_start = time.perf_counter()
        for s in pipe.all_sinks:
            s.open()
        pipe.source.open()
        try:
            from flink_tpu.datastream.window.assigners import (
                CountWindowAssigner, GlobalWindows,
            )

            if self.env.config.get_str("dcn.coordinator", ""):
                if pipe.stages:
                    raise stages_mod.StageGraphError(
                        "multi-stage keyed chains are single-host for now "
                        "— the DCN lockstep plane runs one keyed stage"
                    )
                handle = self._run_dcn(pipe, metrics, job_name,
                                       restore_from)
            elif pipe.stages:
                # chained keyed windowed stages: StageGraph.from_pipeline
                # validates every edge up front (loud setup-time errors
                # naming the unsupported edge) before any compile work
                handle = self._run_windowed(
                    pipe, metrics, job_name, restore_from,
                    graph=stages_mod.StageGraph.from_pipeline(pipe),
                )
            elif pipe.window_agg is not None and (
                pipe.window_agg.trigger is not None
                or pipe.window_agg.evictor is not None
                or pipe.window_agg.window_fn is not None
                or isinstance(pipe.window_agg.assigner, GlobalWindows)
            ):
                handle = self._run_generic_window(pipe, metrics, job_name,
                                                  restore_from)
            elif pipe.window_agg is not None and getattr(
                pipe.window_agg.assigner, "is_session", False
            ):
                handle = self._run_session(pipe, metrics, job_name,
                                           restore_from)
            elif pipe.window_agg is not None and isinstance(
                pipe.window_agg.assigner, CountWindowAssigner
            ):
                handle = self._run_count(pipe, metrics, job_name, restore_from)
            elif pipe.window_agg is not None:
                handle = self._run_windowed(pipe, metrics, job_name,
                                            restore_from)
            elif pipe.process is not None:
                if self._cep_device_eligible(pipe, restore_from):
                    handle = self._run_cep_device(pipe, metrics, job_name,
                                                  restore_from)
                else:
                    handle = self._run_process(pipe, metrics, job_name,
                                               restore_from)
            elif pipe.rolling is not None:
                handle = self._run_rolling(pipe, metrics, job_name, restore_from)
            else:
                self._run_stateless(pipe, metrics)
                handle = JobHandle(job_name, metrics)
        finally:
            pipe.source.close()
            for s in pipe.all_sinks:
                s.close()
            if self._compile_sink is not None:
                CompileEvents.remove_sink(self._compile_sink)
                self._compile_sink = None
            if self._tracer is not None:
                dump = self.env.config.get_str(
                    "observability.trace-dump", ""
                )
                if dump:
                    try:
                        self._tracer.dump(dump)
                    except OSError:
                        pass   # observability must never kill the job
        metrics.wall_time_s = time.perf_counter() - t_start
        return handle

    # ------------------------------------------------------------------
    def _run_dcn(self, pipe: _Pipeline, metrics: JobMetrics, job_name,
                 restore_from=None):
        """Multi-host execution over the DCN global mesh: the SAME
        program runs in every worker process (ref TaskManager.scala:296
        deployment model); ``dcn.coordinator`` + ``dcn.num-processes`` +
        ``dcn.process-id`` select this path from the standard
        ``env.execute()``. The pipeline's windowed keyed stage lowers to
        a DCNJobSpec; each process ingests ITS source's records and the
        keyed shuffle rides the global-mesh collectives (runtime/dcn.py).

        Supported: event-time tumbling/sliding/session windows over
        integer keys with built-in reduces — the stage kinds the
        cross-host kernels implement. Everything else raises rather than
        silently running single-host."""
        import jax

        from flink_tpu.core.time import TimeCharacteristic
        from flink_tpu.datastream.window.assigners import WindowAssigner
        from flink_tpu.runtime import dcn

        env = self.env
        coord = env.config.get_str("dcn.coordinator")
        nproc = env.config.get_int("dcn.num-processes", 1)
        pid = env.config.get_int("dcn.process-id", 0)
        res_dcn = env.config.get_str("pipeline.resident-loop", "auto")
        # Round 20 (was a config ERROR through round 19): resident-loop
        # on the DCN plane now COMPOSES — each host drains up to
        # ring-depth locally-polled batches per lockstep round in one
        # dispatch, the trip count pmax-agreed ON DEVICE so every
        # process still enters the same all_to_all sequence
        # (runtime/dcn.py _run_resident + step.py
        # build_window_dcn_resident_drain). "on" and "while" both select
        # it; "auto" keeps the single-step lockstep dispatch.
        dcn_resident = res_dcn in ("on", "while")
        if res_dcn == "auto":
            print(
                "flink-tpu: pipeline.resident-loop auto resolves to OFF "
                "on the DCN lockstep plane; multi-host execution keeps "
                "the single-step dispatch fallback",
                file=sys.stderr,
            )
        if env.config.get_str("pipeline.data-parallel", "auto") == "on":
            raise ValueError(
                "pipeline.data-parallel=on is incompatible with the DCN "
                "lockstep plane: the sharded ring drain rides the "
                "resident loop, which the lockstep plane cannot run; "
                "unset it or use pipeline.data-parallel=auto"
            )
        wagg = pipe.window_agg
        if wagg is None or pipe.key_by is None:
            raise NotImplementedError(
                "dcn execution covers windowed keyed stages "
                "(tumbling/sliding/session); run other stage kinds "
                "single-host or restructure the job"
            )
        if env.time_characteristic != TimeCharacteristic.EventTime or (
            pipe.ts_transform is None and not pipe.source.columnar
        ):
            raise NotImplementedError(
                "dcn execution requires event time, with an "
                "assign_timestamps_and_watermarks stage or a columnar "
                "source carrying a timestamp array (the lockstep "
                "watermark is the pmin of per-host event-time watermarks)"
            )
        if (wagg.trigger is not None or wagg.evictor is not None
                or wagg.window_fn is not None
                or wagg.allowed_lateness_ms):
            raise NotImplementedError(
                "dcn execution does not cover custom triggers/evictors/"
                "window functions or allowed lateness — these stage "
                "shapes run single-host (the generic window operator)"
            )
        if wagg.reduce_spec_factory is None:
            raise NotImplementedError(
                "dcn execution requires a reduce aggregation "
                "(sum/min/max/count)"
            )
        red = wagg.reduce_spec_factory()
        if red.kind not in ("sum", "min", "max", "count") or \
                getattr(red, "finalize", None) is not None or \
                tuple(getattr(red, "value_shape", ()) or ()) not in (
                    (), (1,)):
            raise NotImplementedError(
                f"dcn execution supports scalar built-in reduces, not "
                f"{red.kind!r} with value shape "
                f"{getattr(red, 'value_shape', ())!r} (e.g. mean() "
                f"needs the composite-accumulator fire path)"
            )
        if wagg.result_fn is not None:
            raise NotImplementedError(
                "dcn execution does not apply result_fn finalization; "
                "use a plain sum/min/max/count reduce"
            )
        assigner = wagg.assigner
        spec_kw = dict(
            capacity_per_shard=env.state_capacity_per_shard,
            max_parallelism=env.max_parallelism,
            batch_per_host=env.batch_size,
            reduce_kind=red.kind,
            out_of_orderness_ms=(
                getattr(pipe.ts_transform.strategy,
                        "out_of_orderness_ms", 0)
                if pipe.ts_transform is not None else 0
            ),
            origin_ms=env.config.get_int("dcn.origin-ms", 0),
            steps_per_dispatch=env.config.get_int(
                "pipeline.steps-per-dispatch", 1
            ),
            resident=dcn_resident,
            resident_ring_depth=env.config.get_int(
                "pipeline.ring-depth", 16
            ),
        )
        # physical ingest partitioner: the API annotation (.shuffle(),
        # .global_(), .rebalance(), .rescale() before key_by) wins, the
        # dcn.ingest-partitioner config is the fallback; the ring/router
        # side channel gets one host:port per process from
        # dcn.rebalance-addrs
        part = pipe.ingest_partition or env.config.get_str(
            "dcn.ingest-partitioner", "forward")
        if part == "rebalance":
            spec_kw.update(rebalance=True)
        elif part != "forward":
            spec_kw.update(ingest_partitioner=part)
        if part not in ("forward", "rescale") and nproc > 1:
            addrs = env.config.get_str("dcn.rebalance-addrs", "")
            if not addrs:
                raise ValueError(
                    f"ingest partitioner {part!r} needs "
                    f"dcn.rebalance-addrs (one host:port per process)")
            spec_kw.update(rebalance_addrs=addrs.split(","))
        if getattr(assigner, "is_session", False):
            if not assigner.is_event_time:
                raise NotImplementedError(
                    "dcn execution covers event-time sessions only "
                    "(processing-time sessions would close on the host "
                    "clock, not the lockstep watermark)"
                )
            spec_kw.update(window_kind="session",
                           gap_ms=assigner.gap_ms)
        elif isinstance(assigner, WindowAssigner) and \
                assigner.is_event_time:
            spec_kw.update(
                size_ms=assigner.size_ms,
                slide_ms=assigner.slide_ms,
                fires_per_step=env.config.get_int(
                    "window.fires-per-step", 4
                ),
            )
        else:
            raise NotImplementedError(
                f"dcn execution does not cover "
                f"{type(assigner).__name__} windows"
            )

        key_sel = pipe.key_by.key_selector
        extractor = wagg.extractor
        ts_fn = (pipe.ts_transform.timestamp_fn
                 if pipe.ts_transform is not None else None)

        class _PipeSource:
            """Adapts this process's pipeline source to the per-host
            partition contract (poll/snapshot/restore)."""

            def poll(self_, max_records):
                polled, end = pipe.source.poll(max_records)
                if pipe.source.columnar and isinstance(polled, tuple):
                    cols, src_ts = polled
                    if not cols:
                        z = np.zeros(0, np.int64)
                        return z, z, np.zeros(0, np.float32), end
                    for t in pipe.pre_chain:
                        if t.kind != "map":
                            raise NotImplementedError(
                                "columnar sources support only 'map' "
                                "before key_by"
                            )
                        cols = t.fn(cols)
                    keys = np.asarray(key_sel(cols))
                    vals = np.asarray(extractor(cols), np.float32)
                    ts = np.asarray(
                        ts_fn(cols) if ts_fn is not None else src_ts,
                        np.int64,
                    )
                else:
                    elements = _apply_chain(pipe.pre_chain,
                                            self._to_elements(polled))
                    if not elements:
                        z = np.zeros(0, np.int64)
                        return z, z, np.zeros(0, np.float32), end
                    keys = np.asarray([key_sel(e) for e in elements])
                    vals = np.asarray([extractor(e) for e in elements],
                                      np.float32)
                    ts = np.asarray([ts_fn(e) for e in elements],
                                    np.int64)
                if not np.issubdtype(keys.dtype, np.integer):
                    raise NotImplementedError(
                        "dcn execution requires integer keys (the key "
                        "id IS the 64-bit routing identity across "
                        "processes; string keys would need a "
                        "coordinated codec)"
                    )
                metrics.records_in += len(keys)
                return keys.astype(np.int64), ts, vals, end

            def snapshot(self_):
                return pipe.source.snapshot_offsets()

            def restore(self_, state):
                pipe.source.restore_offsets(state)

        spec = dcn.DCNJobSpec(
            source_factory=lambda _pid, _nproc: _PipeSource(),
            **spec_kw,
        )
        if not getattr(jax.distributed, "is_initialized", lambda: False)():
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=nproc,
                process_id=pid,
            )
        if restore_from and (
            not env.checkpoint_dir
            or os.path.abspath(str(restore_from))
            != os.path.abspath(env.checkpoint_dir)
        ):
            # the DCN runner restores the latest GLOBAL cut from the
            # job's own lockstep checkpoint dir; silently substituting it
            # for a named savepoint would resume from different state
            raise NotImplementedError(
                "dcn execution restores from the job's configured "
                "checkpoint directory (the lockstep global cut); pass "
                "restore_from equal to the checkpoint directory, or "
                "point enable_checkpointing at the savepoint"
            )
        ckpt_every = env.checkpoint_interval_steps or 0
        runner = dcn.runner_for_spec(
            spec, pid, nproc,
            checkpoint_dir=env.checkpoint_dir or None,
            ckpt_every=ckpt_every,
            restore=bool(restore_from),
        )
        out = runner.run()
        metrics.steps = out["cycles"]
        metrics.dcn_ingested_local = int(out.get("ingested_local", 0))
        is_session = spec_kw.get("window_kind") == "session"
        rows = []
        for k64, st_, en_, v in zip(
                out["key_id"], out["window_start_ms"],
                out["window_end_ms"], out["value"]):
            key = int(np.int64(np.uint64(k64)))
            if is_session:
                rows.append(SessionResult(key, int(st_), int(en_),
                                          float(v)))
            else:
                rows.append(WindowResult(key, int(en_), float(v)))
        metrics.fires += len(rows)
        _emit_batch(pipe, rows, metrics)
        return JobHandle(job_name, metrics)

    # ------------------------------------------------------------------
    def _run_stateless(self, pipe: _Pipeline, metrics: JobMetrics):
        B = self.env.batch_size
        while True:
            self._poll_control()
            polled, end = pipe.source.poll(B)
            elements = self._to_elements(polled)
            metrics.records_in += len(elements)
            elements = _apply_chain(pipe.pre_chain, elements)
            _emit_batch(pipe, elements, metrics)
            metrics.steps += 1
            if end:
                break

    _to_elements = staticmethod(to_elements)

    # ------------------------------------------------------------------
    def _run_windowed(self, pipe: _Pipeline, metrics: JobMetrics, job_name,
                      restore_from=None, graph=None):
        from flink_tpu.core.time import TimeCharacteristic

        env = self.env
        wagg = pipe.window_agg
        assigner = wagg.assigner
        # -- chained stage graph (runtime/stages.py, round 16): when the
        # pipeline carries downstream keyBy→window stages, `graph` is the
        # validated StageGraph and the resident drain becomes the chained
        # variant (step.build_window_chained_drain*): stage-N fires are
        # re-keyed on device and applied to stage-N+1 inside the same
        # count-gated scan, so a 2-stage pipeline still costs one host
        # dispatch per ring drain. Sinks observe the FINAL stage's fires;
        # emit_wagg carries that stage's result_fn/codec semantics.
        emit_wagg = graph.stages[-1].wagg if graph is not None else wagg
        chain_specs: List[Any] = []   # downstream WindowStageSpecs (setup)
        chain_states: List[Any] = []  # downstream device states
        event_time = assigner.is_event_time and (
            env.time_characteristic == TimeCharacteristic.EventTime
        )

        n_dev = len(jax.devices())
        n_shards = max(1, min(env.parallelism, n_dev))
        ctx = MeshContext.create(n_shards, env.max_parallelism)
        # controller-chosen heat-balanced key-group slicing (ISSUE 19):
        # holds the (start, end) pairs the NEXT _replan_mesh installs,
        # persisting a rebalance across subsequent setups; None = the
        # uniform slicing. A shard-COUNT change (elastic loss/scale-up)
        # drops it — the heat evidence it encoded was per-shard.
        kg_slices_hold = [None]
        # -- elastic survival (runtime/elastic.py; ISSUE 8): device loss
        # re-plans the job over the surviving shards instead of crash-
        # looping at a parallelism the mesh no longer has. The
        # controller is the operator/web surface: degraded-state ledger
        # + the scale-back-up request box the step loop polls.
        from flink_tpu.core.config import CoreOptions as _ECO

        elastic_enabled = env.config.get(_ECO.RECOVERY_ELASTIC)
        elastic_min_shards = max(1, env.config.get(_ECO.RECOVERY_MIN_SHARDS))
        elastic_ctl = elastic.ElasticityController(
            list(np.asarray(ctx.mesh.devices).flat)
        )
        env._elasticity_report = elastic_ctl.report
        env._elastic_controller = elastic_ctl

        red = wagg.reduce_spec_factory()
        # time domain: 1 tick = 1 ms until first batch fixes the origin
        td: Optional[TimeDomain] = None
        size_ms, slide_ms = assigner.size_ms, assigner.slide_ms

        win = None
        spec = None
        # compiled update-step variants: steps_by_route[route][tier] with
        # route in {"mask", "exchange"} (record routing to owning shards)
        # and tier in {"insert", "fast"} (adaptive step tiering); the host
        # picks a variant per micro-batch at zero switch cost (shared
        # state layout)
        steps_by_route = {}
        # -- dispatch fusion (pipeline.steps-per-dispatch=K): the fused
        # slot collects K consecutive same-route planned batches and ONE
        # lax.scan megastep applies them in a single dispatch, dividing
        # the fixed dispatch/tracing/watchdog overhead by K. K=1 keeps
        # the single-step path untouched. megasteps_by_route mirrors
        # steps_by_route's [route][tier] shape.
        k_fuse = max(1, env.config.get_int("pipeline.steps-per-dispatch", 1))
        megasteps_by_route = {}
        # -- resident pipeline (pipeline.fused-fire): fold the fire sweep
        # into the megastep scan so a pane-boundary crossing inside a
        # K-group fires WITHIN the scan — the fused slot no longer breaks
        # groups at fire boundaries, and fire payloads surface as LAGGED
        # megastep outputs (fire_watch) instead of a separate serialized
        # fire dispatch. off = the PR-5 split-dispatch path, which always
        # remains the fallback for partial groups and the DCN lockstep
        # plane. Read through the declared ConfigOption (strict coercion).
        from flink_tpu.core.config import CoreOptions as _CoreOpts

        ff_cfg = str(env.config.get(_CoreOpts.PIPELINE_FUSED_FIRE))
        if ff_cfg not in ("auto", "on", "off"):
            raise ValueError(
                f"pipeline.fused-fire must be auto|on|off, got {ff_cfg!r}"
            )
        use_fused_fire = k_fuse > 1 and ff_cfg != "off"
        fire_watch = deque()   # lagged fused-fire payload handles
        FIRE_LAG = 1           # dispatches a payload may stay unread
        fused = ingest_mod.FusedBatchAccumulator(
            k_fuse, hold_fires=use_fused_fire
        )
        fuse_gauge = [None]    # settable steps_per_dispatch gauge
        # -- device-resident steady-state loop (pipeline.resident-loop,
        # round 12): the prefetch thread publishes staged batches into a
        # DeviceBatchRing (runtime/ingest.py) and the accumulated drain
        # group — capacity = ring depth — dispatches as ONE count-gated
        # resident-drain scan (runtime/step.py), so steady state costs
        # one host round trip per ring drain instead of one per
        # megastep. Config validated here; `use_resident` is FINALIZED
        # where prefetch/staging resolve (just before the ingest
        # pipeline is built) because the drain consumes ring-published
        # staged batches. The DCN lockstep plane runs a separate
        # executor entirely (_run_dcn) and keeps its loud single-step
        # fallback there.
        res_cfg = str(env.config.get(_CoreOpts.PIPELINE_RESIDENT_LOOP))
        if res_cfg not in ("auto", "on", "while", "off"):
            raise ValueError(
                f"pipeline.resident-loop must be auto|on|while|off, "
                f"got {res_cfg!r}"
            )
        ring_depth = max(2, env.config.get_int("pipeline.ring-depth", 16))
        # early-exit while-drain (pipeline.resident-loop=while, ISSUE
        # 20): the drain's trip count re-reads the ring's HBM publish
        # cursor inside the loop condition, bounded per dispatch by
        # while-drain.max-slots — the bound (not the observed fill) is
        # what the watchdog arms and the flight recorder sizes to, and
        # the drain GROUP capacity grows to the bound so publishes
        # landing while the previous drain was in flight join the
        # current dispatch instead of forcing a new one. 0 sizes the
        # bound to 2x ring depth (never below ring depth).
        wd_max_slots = env.config.get_int(
            "pipeline.while-drain.max-slots", 0)
        if wd_max_slots <= 0:
            wd_max_slots = 2 * ring_depth
        wd_max_slots = max(ring_depth, wd_max_slots)
        wd_cpu_override = env.config.get_str(
            "pipeline.while-drain.cpu-override", "off") == "on"
        use_while = False          # finalized with use_resident
        use_resident = False       # finalized at ingest construction
        residents_by_route = {}    # [route][tier] resident-drain kernels
        pending_batch = [None]     # greedy ring fill's non-drain leftover
        drain_warmup = [False]     # warmup drains skip the chaos seam
        # -- mesh-resident data parallelism (pipeline.data-parallel,
        # round 13): each chip owns a contiguous key-group slice, the
        # prefetch thread routes records to the owning shard and
        # publishes into that shard's slice of a ShardedDeviceBatchRing,
        # and one shard_map'd drain advances every shard's ring with
        # zero cross-chip collectives in the keyed body (fires pack
        # per-shard and merge host-side on the lagged consume path).
        # Validated here; `use_dp` is FINALIZED with use_resident.
        dp_cfg = str(env.config.get(_CoreOpts.PIPELINE_DATA_PARALLEL))
        if dp_cfg not in ("auto", "on", "off"):
            raise ValueError(
                f"pipeline.data-parallel must be auto|on|off, "
                f"got {dp_cfg!r}"
            )
        dp_capf = env.config.get_float("pipeline.shard-capacity-factor", 2.0)
        if dp_capf < 1.0:
            raise ValueError(
                f"pipeline.shard-capacity-factor must be >= 1.0, "
                f"got {dp_capf}"
            )
        use_dp = False             # finalized at ingest construction
        shard_cap = [0]            # per-shard ring-slice rows (dp only)
        # -- update-kernel pre-combine (pipeline.update-precombine):
        # duplicate-key collapse before the state scatter (wk.update);
        # generic reduces already pre-aggregate, sketches expand per
        # register. auto is PLATFORM-gated: on accelerators a scatter
        # with duplicate indices serializes (the win), but XLA's CPU
        # sort costs ~4.5ms per 16k lanes (measured, device_update_
        # ceiling bench) — far more than the CPU scatter it would save —
        # so auto keeps the CPU path bit-identical to the unsorted
        # scatter
        pc_cfg = env.config.get_str("pipeline.update-precombine", "auto")
        if pc_cfg not in ("auto", "on", "off"):
            raise ValueError(
                f"pipeline.update-precombine must be auto|on|off, "
                f"got {pc_cfg!r}"
            )
        use_precombine = pc_cfg == "on" or (
            pc_cfg == "auto" and jax.default_backend() != "cpu"
        )
        # -- packed state planes (state.packed-planes): touched bits ride
        # a trailing accumulator column — one scatter/sweep maintains
        # both planes (wk.init_state packed). auto is PLATFORM-gated
        # like precombine: on accelerators the saved scatter pass wins;
        # on CPU the wider sweep bytes cost more than the serial scatter
        # they replace (measured, device_update_ceiling state-plane
        # sweep). Snapshots stay logical, so checkpoints move freely
        # between plane layouts.
        pp_cfg = str(env.config.get(_CoreOpts.STATE_PACKED_PLANES))
        if pp_cfg not in ("auto", "on", "off"):
            raise ValueError(
                f"state.packed-planes must be auto|on|off, got {pp_cfg!r}"
            )
        if pp_cfg == "on" and not wk.packed_eligible(red):
            raise ValueError(
                "state.packed-planes=on requires a builtin sum/count/"
                "min/max reduce with the default neutral and an "
                "at-most-1-D value; unset it for this stage"
            )
        use_packed = pp_cfg == "on" or (
            pp_cfg == "auto" and jax.default_backend() != "cpu"
            and wk.packed_eligible(red)
        )
        # -- tiered key-group state (state.tiers.resident-key-groups):
        # a per-shard budget caps how many key-groups keep device slot
        # rows; the rest live in the host pane stores and ride the
        # overflow ring until promoted. The manager is created in
        # setup() (key-group ranges come from the mesh) and SURVIVES
        # re-plans via rescale() so fault/churn counters span the job.
        tier_budget_cfg = int(
            env.config.get(_CoreOpts.STATE_TIERS_RESIDENT_KEY_GROUPS)
        )
        use_tiers = [False]
        tier_mgr = [None]
        tier_mask_dev = [None]    # device replica of the residency mask
        exchange_cap = [0]        # per-(src,dst) bucket lanes of the exchange
        force_route = [None]      # warmup override
        fire_step = None
        fire_reduced_step = None   # ReducedFires variant (device_reduce sinks)
        state = None
        # key-state layout, decided ONCE (the compiled steps bake it in):
        # "hash" | "direct" | "auto" (resolved from the first batch's key
        # identities in setup(); see wk.init_state layout="direct")
        layout_cfg = env.config.get_str("state.backend.layout", "auto")
        if layout_cfg not in ("auto", "hash", "direct"):
            raise ValueError(
                f"state.backend.layout must be auto|hash|direct, "
                f"got {layout_cfg!r}"
            )
        layout = [None]
        # set by poll_cycle from the first batch's key identities; setup()
        # combines it with spillability to resolve layout "auto"
        auto_direct_hint = [False]
        # adaptive step tiering (see wk.update insert flag): holders are
        # 1-element lists so nested closures can flip them
        step_mode = ["insert"]
        tier_quiet = [0]          # consecutive zero-activity lagged checks
        # checks are SAMPLED every MON_EVERY steps, so 2 quiet checks span
        # ~2*MON_EVERY steps of genuinely quiet stream before the switch
        TIER_QUIET_CHECKS = 2
        # futile-bounce damping: when a fast->insert bounce places NOTHING
        # (the misses were chain-exhausted keys insert can never place),
        # tolerate that miss level in fast mode instead of bouncing
        # forever; reset when compaction/restore may change placeability
        miss_tolerance = [0]
        bounce_miss = [0]         # miss count that triggered current bounce
        bounce_placed = [False]   # did the bounce place any key?
        # step lane count: == B, or B rounded up to a multiple of the
        # shard count when the ICI exchange splits the batch over devices
        B_step = [None]
        # reused prefix-mask template (ingest.make_prefix_mask_template):
        # the per-batch np.ones+pad valid mask becomes a view slice —
        # one allocation per stage, immutable, safe under async transfer
        valid_tmpl = [None]
        codec = KeyCodec()
        # reverse key map costs a python dict insert per record; benchmarks
        # and columnar sinks that accept 64-bit key ids can turn it off
        keep_rev = env.config.get_bool("keys.reverse-map", True)
        B = env.batch_size
        wm_strategy = (
            pipe.ts_transform.strategy if pipe.ts_transform is not None
            else WatermarkStrategy.for_monotonous_timestamps()
        )
        # doctor recompile baseline: steady-bucket snapshot re-pinned at
        # the end of every setup() so only post-build compiles count
        _doctor_steady0 = [{"count": 0, "time_ms": 0.0}]

        def setup(origin_ms: int, fresh_state: bool = True):
            nonlocal td, win, spec, fire_step, fire_reduced_step, state
            td = TimeDomain(origin_ms=origin_ms, ms_per_tick=1)
            ppw = size_ms // slide_ms
            ring_cfg = env.config.get_int("window.ring-panes", 0)
            if ring_cfg and ring_cfg < ppw + 3:
                # the catch-up slicer's span bound is
                # ring - max(2, panes_per_window + 1); below 1 its
                # grouping loop can never advance (each group would be
                # empty forever) — fail loudly at setup instead of
                # hanging the job on the first replay burst
                raise ValueError(
                    f"window.ring-panes={ring_cfg} leaves no catch-up "
                    f"headroom for a {ppw}-pane window (need ring >= "
                    f"panes_per_window + 3 = {ppw + 3}); raise it or "
                    f"unset it to use the auto-sized ring"
                )
            ring = ring_cfg or max(
                8,
                2 * ppw
                + (wm_strategy.out_of_orderness_ms + wagg.allowed_lateness_ms)
                // slide_ms
                + 2,
            )
            # overflow ring: spill-tier support for builtin float32 scalar
            # reduces (kill the hard over-capacity failure; VERDICT item 7)
            ovf = 0
            spillable = (
                wk.overflow_supported(red)
                and jnp.zeros((), red.dtype).dtype == jnp.float32
                and len(red.value_shape) <= 1
                # the spill tier cannot replay late re-fires for evicted
                # keys (host stores carry no freshness); with allowed
                # lateness the job keeps strict-capacity semantics instead
                # of being silently wrong for that corner
                and wagg.allowed_lateness_ms == 0
                # chained stage graphs keep strict capacity: a spill-tier
                # eviction on stage 0 would have to replay through every
                # downstream stage (host stores carry no edge lineage)
                and graph is None
            )
            # -1/unset = auto: absorbs the full sampled-lagged detection
            # window of full-batch overflow (MON_EVERY*(OVF_LAG+1) steps
            # between a miss and its drain, plus dispatch slack) with no
            # loss; 0 disables; an explicit positive value wins (and may
            # lose under sustained pressure, surfaced by the
            # strict-capacity error)
            ovf_cfg = env.config.get_int("state.backend.overflow-ring", -1)
            if ovf_cfg > 0 and not spillable:
                raise ValueError(
                    "state.backend.overflow-ring is set but this window "
                    "stage cannot use the spill tier (requires a builtin "
                    "float32 sum/count/min/max reduce without finalize and "
                    "allowed lateness 0); unset it to run with strict "
                    "capacity"
                )
            if spillable:
                # + k_fuse: a fused group's misses can only drain at the
                # megastep boundary, so the detection window stretches by
                # up to one group of batches. The sample stride is
                # ceil(MON_EVERY / K) * K batches, not MON_EVERY: the
                # skip counter advances K at a time and resets on
                # crossing, so samples land only on dispatch boundaries
                # (K=7 with MON_EVERY=8 samples every 14 batches)
                # with the resident loop on the dispatch group is the
                # RING, so the detection window stretches by up to one
                # ring of batches, not one K-group
                grp_k = ring_depth if use_resident else k_fuse
                stride = -(-MON_EVERY // grp_k) * grp_k
                auto = (stride * (OVF_LAG + 1) + 4 + grp_k) * B + 8192
                ovf = ovf_cfg if ovf_cfg >= 0 else auto
            # tiered state rides the spill tier: a non-resident lane
            # diverts to the overflow ring and folds into the same host
            # pane stores, so every spill-tier precondition is a tier
            # precondition too (and the ring must actually exist)
            if tier_budget_cfg > 0 and not (spillable and ovf):
                raise ValueError(
                    "state.tiers.resident-key-groups is set but this "
                    "window stage cannot run tiered state (requires the "
                    "spill tier: a builtin float32 sum/count/min/max "
                    "reduce without finalize, allowed lateness 0, no "
                    "chained stage graph, and a non-zero overflow "
                    "ring); unset it to keep every key-group resident"
                )
            use_tiers[0] = tier_budget_cfg > 0
            win = wk.WindowSpec(
                size_ticks=size_ms, slide_ticks=slide_ms,
                ring=ring,
                # F window-ends evaluated per fire step: each lane costs 3
                # full-capacity pack scatters, so fewer lanes = cheaper
                # boundary drains; catch-up replay just loops more drains
                fires_per_step=env.config.get_int("window.fires-per-step", 4),
                lateness_ticks=wagg.allowed_lateness_ms,
                overflow=ovf,
            )
            if layout[0] is None:
                if layout_cfg != "auto":
                    layout[0] = layout_cfg
                else:
                    # auto picks direct only when the spill tier exists to
                    # absorb later out-of-bound keys; a non-spillable
                    # stage (e.g. allowed lateness > 0, generic reduce)
                    # would DROP them where the hash layout would simply
                    # insert them
                    layout[0] = (
                        "direct" if auto_direct_hint[0] and spillable
                        else "hash"
                    )
            spec = WindowStageSpec(
                win=win, red=red,
                capacity_per_shard=env.state_capacity_per_shard,
                probe_len=env.config.get_int("state.probe-len", 16),
                layout=layout[0],
                precombine=use_precombine,
                packed=use_packed,
            )
            metrics.state_layout = layout[0]
            metrics.state_packed_planes = use_packed
            if use_tiers[0]:
                starts_t, ends_t = ctx.kg_bounds()
                if tier_mgr[0] is None:
                    tier_mgr[0] = tiers_mod.TierManager(
                        ctx.max_parallelism, starts_t, ends_t,
                        tier_budget_cfg,
                        prefetch_ahead_panes=int(env.config.get(
                            _CoreOpts.STATE_TIERS_PREFETCH_AHEAD_PANES
                        )),
                        min_dwell_cycles=int(env.config.get(
                            _CoreOpts.STATE_TIERS_MIN_DWELL_CYCLES
                        )),
                        max_swaps_per_cycle=int(env.config.get(
                            _CoreOpts.STATE_TIERS_MAX_SWAPS_PER_CYCLE
                        )),
                    )
                else:
                    # elastic re-plan / restore: re-slice residency to
                    # the new shard ranges, keep the job-lifetime
                    # counters (faults/churn feed the doctor rule)
                    tier_mgr[0].rescale(starts_t, ends_t)
                tier_mask_dev[0] = jnp.asarray(tier_mgr[0].mask())
                if self._job_group is not None:
                    grp_t = self._job_group

                    def _tier_ctr(field):
                        tm = tier_mgr[0]
                        return int(getattr(tm, field)) if tm else 0

                    def _tier_res():
                        tm = tier_mgr[0]
                        return tm.resident_groups() if tm else 0

                    # idempotent like the drain gauges (register
                    # overwrites), re-run per setup for elastic re-plans
                    grp_t.gauge("tier_resident_groups", _tier_res)
                    grp_t.gauge("tier_faults",
                                partial(_tier_ctr, "tier_faults"))
                    grp_t.gauge("tier_prefetch_hits",
                                partial(_tier_ctr, "prefetch_hits"))
                    grp_t.gauge("tier_prefetch_misses",
                                partial(_tier_ctr, "prefetch_misses"))
            if graph is not None:
                # plan the downstream stages off stage 0's spec (identity
                # re-key: every stage shares the codec/layout/capacity,
                # fires stay shard-local) and reject runtime shapes the
                # chained drain cannot serve — loudly, naming the knob,
                # before any compile work
                # drain_depth sizes the downstream pane rings: the
                # chained drain advances stages 1..N-1 once per drain,
                # so they must absorb a whole ring's worth of upstream
                # fires between advances
                chain_specs[:] = graph.plan_specs(
                    spec, drain_depth=ring_depth
                )
                graph.check_runtime(
                    use_resident=use_resident,
                    overflow_lanes=ovf,
                    drain_stats=drain_stats_on,
                    reduced_fires=sink_device_reduce,
                    max_stages=env.config.get(
                        _CoreOpts.PIPELINE_STAGES_MAX_STAGES
                    ),
                )
            if not steps_by_route:
                # exchange.mode — how records reach their owning shard on
                # a multi-device mesh (the reference's keyed shuffle,
                # KeyGroupStreamPartitioner.java:53):
                #   "auto" (default): PER-BATCH adaptive. The host computes
                #     exact shard counts for each batch (cheap numpy) and
                #     dispatches the O(B/n)-per-device all_to_all step only
                #     when every shard's records provably fit its static
                #     bucket; skewed batches take the replicate-and-mask
                #     step instead. Never lossy, scalable whenever the
                #     batch actually balances.
                #   "all_to_all": always exchange; bucket overflow is
                #     counted into dropped_capacity (strict-capacity
                #     surfaces it).
                #   "mask": always replicate-and-mask (O(B) per chip).
                # The batch auto-pads up to a multiple of the shard count.
                mode = env.config.get_str("exchange.mode", "auto")
                if mode not in ("auto", "all_to_all", "mask"):
                    raise ValueError(
                        f"exchange.mode must be auto|all_to_all|mask, "
                        f"got {mode!r}"
                    )
                if graph is not None and mode == "all_to_all":
                    raise stages_mod.StageGraphError(
                        "exchange.mode=all_to_all is not supported with "
                        "chained stage graphs — the identity re-key keeps "
                        "fires shard-local, so the chained drain runs the "
                        "replicate-and-mask route; unset exchange.mode"
                    )
                want_ex = (
                    ctx.n_shards > 1 and mode in ("auto", "all_to_all")
                    and graph is None
                )
                B_step[0] = (
                    ((B + ctx.n_shards - 1) // ctx.n_shards) * ctx.n_shards
                    if want_ex else B
                )
                metrics.exchange_mode = (
                    "adaptive" if want_ex and mode == "auto"
                    else "all_to_all" if want_ex else "mask"
                )
                build_fast = spillable and win.overflow and \
                    layout[0] != "direct"
                if graph is not None:
                    # chained jobs dispatch ONLY through the chained
                    # resident drain — a plain per-batch step would
                    # advance stage 0 without feeding stage 1, so no
                    # single-step kernel exists; the placeholder keeps
                    # the route table (and the ingest plan's route
                    # tuple) shaped like the single-stage path
                    steps_by_route["mask"] = {"insert": None, "fast": None}
                elif not want_ex or mode == "auto":
                    steps_by_route["mask"] = {
                        "insert": build_window_update_step(
                            ctx, spec, kg_fill=kg_stats_on,
                            tiered=use_tiers[0],
                        ),
                        "fast": build_window_update_step(
                            ctx, spec, insert=False, kg_fill=kg_stats_on,
                            tiered=use_tiers[0],
                        ) if build_fast else None,
                    }
                if want_ex:
                    bpd = B_step[0] // ctx.n_shards
                    capf = env.config.get_float("exchange.capacity-factor",
                                                2.0)
                    ex_insert = build_window_update_step_exchange(
                        ctx, spec, bpd, capf, kg_fill=kg_stats_on,
                        tiered=use_tiers[0],
                    )
                    steps_by_route["exchange"] = {
                        "insert": ex_insert,
                        "fast": build_window_update_step_exchange(
                            ctx, spec, bpd, capf, insert=False,
                            kg_fill=kg_stats_on, tiered=use_tiers[0],
                        ) if build_fast else None,
                    }
                    exchange_cap[0] = ex_insert.bucket_cap
                if k_fuse > 1 and graph is None:
                    # K-fused megasteps mirror the [route][tier] variant
                    # table for exactly the routes built above; partial
                    # groups fall back to the single steps (bit-identical
                    # by construction). With the resident pipeline on
                    # (pipeline.fused-fire) the FIRED variants replace
                    # the plain ones outright — full groups always take
                    # the in-scan fire path, so compiling both would
                    # only double the warmup burst.
                    if use_fused_fire:
                        # device_reduce sink topologies never read fire
                        # payloads, so their fired megasteps surface
                        # ReducedFires and skip the [K, F, C] payload
                        # stacking entirely (the in-scan analog of
                        # fire_reduced_step). Only safe when the spill
                        # tier can NEVER activate (no overflow ring):
                        # spill merges need per-key payloads.
                        ff_reduced = bool(
                            sink_device_reduce and not win.overflow
                        )
                        mk_mask = partial(
                            build_window_megastep_fired,
                            reduced=ff_reduced,
                        )
                        mk_ex = partial(
                            build_window_megastep_fired_exchange,
                            reduced=ff_reduced,
                        )
                    else:
                        mk_mask = build_window_megastep
                        mk_ex = build_window_megastep_exchange
                    if "mask" in steps_by_route:
                        megasteps_by_route["mask"] = {
                            "insert": mk_mask(
                                ctx, spec, k_fuse, kg_fill=kg_stats_on,
                                tiered=use_tiers[0],
                            ),
                            "fast": mk_mask(
                                ctx, spec, k_fuse, insert=False,
                                kg_fill=kg_stats_on, tiered=use_tiers[0],
                            ) if build_fast else None,
                        }
                    if "exchange" in steps_by_route:
                        megasteps_by_route["exchange"] = {
                            "insert": mk_ex(
                                ctx, spec, bpd, k_fuse, capf,
                                kg_fill=kg_stats_on, tiered=use_tiers[0],
                            ),
                            "fast": mk_ex(
                                ctx, spec, bpd, k_fuse, capf,
                                insert=False, kg_fill=kg_stats_on,
                                tiered=use_tiers[0],
                            ) if build_fast else None,
                        }
                if use_resident and graph is not None:
                    # chained resident drain (round 16): ONE count-gated
                    # scan advances EVERY stage — stage-N fire lanes are
                    # re-keyed on device (cumsum+searchsorted+gather)
                    # and applied to stage-N+1 inside the same scan, so
                    # the whole chain costs one host dispatch per ring
                    # drain. Insert tier only: the fast tier's miss
                    # contract needs the overflow ring, which chained
                    # jobs run without (strict capacity).
                    ex_lanes = env.config.get(
                        _CoreOpts.PIPELINE_STAGES_EXCHANGE_LANES
                    )
                    all_specs = (spec,) + tuple(chain_specs)
                    residents_by_route["mask"] = {
                        "insert": build_window_chained_drain(
                            ctx, all_specs, ring_depth,
                            kg_fill=kg_stats_on,
                            exchange_lanes=ex_lanes,
                            drain_stats=drain_stats_on,
                        ),
                        "fast": None,
                    }
                    if use_dp:
                        shard_cap[0] = bucket_capacity(
                            B_step[0], ctx.n_shards, dp_capf
                        )
                        residents_by_route["sharded"] = {
                            "insert": build_window_chained_drain_sharded(
                                ctx, all_specs, ring_depth,
                                kg_fill=kg_stats_on,
                                exchange_lanes=ex_lanes,
                                drain_stats=drain_stats_on,
                            ),
                            "fast": None,
                        }
                        if self._job_group is not None:
                            # same idempotent per-shard refusal gauges
                            # as the single-stage sharded ring below
                            for _s in range(ctx.n_shards):
                                self._job_group.gauge(
                                    f"ring_publish_refusals_shard_{_s}",
                                    partial(_ring_refusals, _s),
                                )
                elif use_resident:
                    # resident ring-drain kernels (pipeline.resident-
                    # loop): ONE count-gated scan per route x tier
                    # serves EVERY fill level 1..ring_depth — the host
                    # passes the live slot count as a traced operand,
                    # so partial drains never recompile. Fired variants
                    # only: the drain is the fused-fire pipeline taken
                    # to its limit (every slot fires under its own
                    # watermark inside the scan).
                    rd_reduced = bool(
                        sink_device_reduce and not win.overflow
                    )
                    # while mode (ISSUE 20): mask + sharded routes swap
                    # the count-gated scan for the early-exit while
                    # drain sized to the while-drain BOUND; the exchange
                    # route keeps the scan kernel (the all_to_all in a
                    # data-dependent while body is not worth the
                    # collective-under-while hazard) but is sized to the
                    # same bound so while-mode drain groups fit it
                    drain_depth = wd_max_slots if use_while else ring_depth
                    if "mask" in steps_by_route:
                        if use_while:
                            residents_by_route["mask"] = {
                                "insert": build_window_while_drain(
                                    ctx, spec, wd_max_slots,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ),
                                "fast": build_window_while_drain(
                                    ctx, spec, wd_max_slots, insert=False,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ) if build_fast else None,
                            }
                        else:
                            residents_by_route["mask"] = {
                                "insert": build_window_resident_drain(
                                    ctx, spec, ring_depth,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ),
                                "fast": build_window_resident_drain(
                                    ctx, spec, ring_depth, insert=False,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ) if build_fast else None,
                            }
                    if "exchange" in steps_by_route:
                        residents_by_route["exchange"] = {
                            "insert": build_window_resident_drain_exchange(
                                ctx, spec, bpd, drain_depth, capf,
                                kg_fill=kg_stats_on, reduced=rd_reduced,
                                drain_stats=drain_stats_on,
                                tiered=use_tiers[0],
                            ),
                            "fast": build_window_resident_drain_exchange(
                                ctx, spec, bpd, drain_depth, capf,
                                insert=False, kg_fill=kg_stats_on,
                                reduced=rd_reduced,
                                drain_stats=drain_stats_on,
                                tiered=use_tiers[0],
                            ) if build_fast else None,
                        }
                    if use_dp:
                        # shard_map'd drain (pipeline.data-parallel):
                        # records arrive PRE-ROUTED to the owning
                        # shard's ring slice, so the drained body runs
                        # shard-local with ZERO collectives (the
                        # ownership mask is a safety net, not a
                        # router) and each shard gates on its OWN
                        # count — one slow shard never pads the
                        # others' drains.
                        shard_cap[0] = bucket_capacity(
                            B_step[0], ctx.n_shards, dp_capf
                        )
                        if use_while:
                            residents_by_route["sharded"] = {
                                "insert": build_window_while_drain_sharded(
                                    ctx, spec, wd_max_slots,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ),
                                "fast": build_window_while_drain_sharded(
                                    ctx, spec, wd_max_slots, insert=False,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ) if build_fast else None,
                            }
                        else:
                            residents_by_route["sharded"] = {
                                "insert": build_window_sharded_drain(
                                    ctx, spec, ring_depth,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ),
                                "fast": build_window_sharded_drain(
                                    ctx, spec, ring_depth, insert=False,
                                    kg_fill=kg_stats_on,
                                    reduced=rd_reduced,
                                    drain_stats=drain_stats_on,
                                    tiered=use_tiers[0],
                                ) if build_fast else None,
                            }
                        if self._job_group is not None:
                            # per-shard refusal gauges live here (not
                            # the main gauges block) so they track the
                            # mesh size across elastic re-plans;
                            # registry.register overwrites, so the
                            # repeat registration is idempotent — and
                            # a scale-DOWN re-plan removes the series
                            # of shards that no longer exist (ISSUE 19
                            # bugfix: stale gauges reported the dead
                            # mesh forever)
                            for _s in range(ctx.n_shards):
                                self._job_group.gauge(
                                    f"ring_publish_refusals_shard_{_s}",
                                    partial(_ring_refusals, _s),
                                )
                            for _s in range(ctx.n_shards,
                                            refusal_gauge_n[0]):
                                self._job_group.remove(
                                    f"ring_publish_refusals_shard_{_s}"
                                )
                            refusal_gauge_n[0] = ctx.n_shards
                if use_resident and drain_stats_on:
                    # drain flight recorder, host half: the
                    # aggregator the lagged consume path feeds,
                    # plugged into the attribution as its resident-
                    # loop regime signal — single-stage AND chained
                    # drains (stage-aware since ISSUE 17). Rebuilt per
                    # setup() so an elastic re-plan resizes the
                    # per-shard series with the mesh. Lane count
                    # follows the RING: per-shard with the sharded
                    # ring (use_dp), one global lane otherwise
                    # (absorb_payload folds the payload's shard rows
                    # to match).
                    n_lanes = ctx.n_shards if use_dp else 1
                    n_stages_t = (
                        1 + len(chain_specs) if graph is not None else 1
                    )
                    ex_lanes_t = env.config.get(
                        _CoreOpts.PIPELINE_STAGES_EXCHANGE_LANES
                    ) if graph is not None else 0
                    drain_telem[0] = DrainTelemetry(
                        n_lanes, ring_depth, tracer=tracer,
                        n_stages=n_stages_t,
                        exchange_lanes=ex_lanes_t,
                        key_groups=maxp_kg if kg_stats_on else 0,
                        kg_alpha=env.config.get(
                            _CoreOpts.KG_HEAT_ALPHA
                        ),
                    )
                    ds_skip[0] = 0
                    if self._attribution is not None:
                        self._attribution.resident_fn = (
                            drain_telem[0].regime
                        )
                    if self._job_group is not None:
                        grp_d = self._job_group

                        def _dt_fill(s):
                            dt = drain_telem[0]
                            return dt.slot_fill(s) if dt else 0

                        def _dt_duty(s):
                            dt = drain_telem[0]
                            return (
                                round(dt.duty_cycle(s), 4)
                                if dt else 0.0
                            )

                        def _dt_lat(which, q):
                            dt = drain_telem[0]
                            if dt is None:
                                return 0.0
                            v = (
                                dt.fire_latency_ms(q)
                                if which == "fire"
                                else dt.consume_latency_ms(q)
                            )
                            return round(v, 3) if v is not None else 0.0

                        # same idempotency story as the refusal
                        # series above (registry.register overwrites;
                        # shards dropped by a re-plan unregister)
                        for _s in range(n_lanes):
                            grp_d.gauge(
                                f"drain_slot_fill_shard_{_s}",
                                partial(_dt_fill, _s),
                            )
                            grp_d.gauge(
                                f"drain_duty_cycle_shard_{_s}",
                                partial(_dt_duty, _s),
                            )
                        for _s in range(n_lanes, drain_gauge_n[0]):
                            grp_d.remove(f"drain_slot_fill_shard_{_s}")
                            grp_d.remove(f"drain_duty_cycle_shard_{_s}")
                        drain_gauge_n[0] = n_lanes
                        for _q in (50, 95, 99):
                            grp_d.gauge(
                                f"drain_fire_latency_p{_q}_ms",
                                partial(_dt_lat, "fire", float(_q)),
                            )
                            grp_d.gauge(
                                f"drain_consume_latency_p{_q}_ms",
                                partial(_dt_lat, "consume", float(_q)),
                            )

                        def _dt_stage(i, field):
                            dt = drain_telem[0]
                            return dt.stage_stat(i, field) if dt else 0

                        # per-downstream-stage gauges (chained jobs):
                        # edge pressure + coupled-watermark lag per
                        # stage, scraped like any other job gauge
                        for _i in range(1, n_stages_t):
                            for _f in ("edge_events", "fire_lanes",
                                       "dropped_capacity",
                                       "wm_lag_panes"):
                                grp_d.gauge(
                                    f"drain_stage{_i}_{_f}",
                                    partial(_dt_stage, _i, _f),
                                )
                        if kg_stats_on:
                            def _kg_heat(which):
                                dt = drain_telem[0]
                                if dt is None:
                                    return 0.0
                                v = (dt.kg_heat_max() if which == "max"
                                     else dt.kg_heat_skew())
                                return round(v, 4)

                            grp_d.gauge("kg_heat_max",
                                        partial(_kg_heat, "max"))
                            grp_d.gauge("kg_heat_skew_ratio",
                                        partial(_kg_heat, "skew"))
                if graph is not None:
                    # NO standalone fire step for chained jobs: a bare
                    # fire sweep would consume stage-0 fires without
                    # feeding them to stage 1. Every fire — steady state
                    # and end-of-stream flush — goes through the chained
                    # drain (drain_fires' chained branch dispatches
                    # empty drain rounds to sweep out residual panes).
                    fire_step = None
                else:
                    fire_step = build_window_fire_step(ctx, spec)
                if sink_device_reduce and graph is None:
                    # a second compiled fire variant with NO key/value
                    # packing; the drain picks per-iteration (the spill
                    # tier may appear mid-job, forcing the full variant)
                    fire_reduced_step = build_window_fire_reduced_step(
                        ctx, spec
                    )
            # -- ingest plan (runtime/ingest.py): publish the time domain,
            # lane geometry, exchange capacity and route shardings so the
            # prep side can route-plan and device-stage batches off the
            # step-loop thread. (Re-)installed on every setup — a restore
            # changes the time-domain origin; the producer is paused there
            # so the swap never races a batch mid-prep.
            valid_tmpl[0] = ingest_mod.make_prefix_mask_template(B_step[0])
            mask_sh, split_sh = ingest_mod.IngestPlan.shardings_for(ctx.mesh)
            ingest.set_plan(ingest_mod.IngestPlan(
                td=td, slide_ticks=int(win.slide_ticks),
                span_limit=win.ring - max(
                    2, int(win.size_ticks // win.slide_ticks) + 1
                ),
                B=B, B_step=B_step[0], n_shards=ctx.n_shards,
                max_parallelism=ctx.max_parallelism, kg_ends=_kg_ends,
                exchange_cap=exchange_cap[0],
                routes=tuple(steps_by_route) + (
                    ("sharded",) if use_dp else ()
                ),
                staging=use_staging,
                mask_sharding=mask_sh, split_sharding=split_sh,
                value_shape=(
                    () if red.kind == "sketch" else tuple(red.value_shape)
                ),
                value_dtype=(
                    np.uint32 if red.kind == "sketch" else np.float32
                ),
                ring_depth=ring_depth if use_resident else 0,
                shard_cap=shard_cap[0] if use_dp else 0,
            ))
            if use_while and ingest.device_ring is not None:
                # stand up the HBM publish cursor the while-drain's loop
                # condition re-reads: replicated scalar slot for the
                # global ring, one entry per owning chip for the sharded
                # lanes (same shardings the batch operands use)
                ingest.device_ring.enable_device_cursor(
                    split_sh if ingest.device_ring.sharded else mask_sh
                )
            if fresh_state:
                state = init_sharded_state(ctx, spec)
                if graph is not None:
                    chain_states[:] = [
                        init_sharded_state(ctx, cs) for cs in chain_specs
                    ]
                # trigger ALL compiles NOW (inside any benchmark warmup)
                # so neither the first pane-boundary fire nor the first
                # insert->fast tier switch nor the first adaptive route
                # flip is a multi-second compile stall mid-measurement;
                # firing at the MIN-sentinel watermark is a no-op on
                # fresh state
                steps0, fast0, ex0 = (metrics.steps, metrics.steps_fast,
                                      metrics.steps_exchanged)
                fused0 = metrics.fused_dispatches
                ff0 = metrics.fused_fire_dispatches
                rd0 = metrics.resident_drains
                ss0 = metrics.steps_sharded
                for route in steps_by_route:
                    for tier in ("insert", "fast"):
                        if steps_by_route[route][tier] is None:
                            continue
                        step_mode[0] = tier
                        force_route[0] = route
                        # label the compile burst so CompileEvents
                        # attributes it; anything compiling later (the
                        # "steady" bucket) is the recompile-storm alarm
                        with CompileEvents.stage(
                            f"window-update-{route}-{tier}"
                        ):
                            self._empty_step(run_update, B_step[0], red,
                                             None)
                for route in megasteps_by_route:
                    for tier in ("insert", "fast"):
                        if megasteps_by_route[route][tier] is None:
                            continue
                        step_mode[0] = tier
                        with CompileEvents.stage(
                            f"window-megastep-{route}-{tier}"
                        ):
                            run_update_fused(
                                route, [_empty_fused_item(route)
                                        for _ in range(k_fuse)]
                            )
                drain_warmup[0] = True
                try:
                    for route in residents_by_route:
                        # one compile serves every fill level (count is
                        # a traced operand); warm up at a PARTIAL fill
                        # so both cond branches execute at least once
                        # before measurement
                        for tier in ("insert", "fast"):
                            if residents_by_route[route][tier] is None:
                                continue
                            step_mode[0] = tier
                            with CompileEvents.stage(
                                f"window-drain-{route}-{tier}"
                            ):
                                run_update_resident(
                                    route, [_empty_fused_item(route)
                                            for _ in range(ring_depth - 1)]
                                )
                finally:
                    drain_warmup[0] = False
                step_mode[0] = "insert"
                force_route[0] = None
                tier_quiet[0] = 0
                mon_watch.clear()
                # warmup dispatches must not pollute the step counters the
                # operator (and the tiering test) reads
                metrics.steps, metrics.steps_fast = steps0, fast0
                metrics.steps_exchanged = ex0
                metrics.fused_dispatches = fused0
                metrics.fused_fire_dispatches = ff0
                metrics.resident_drains = rd0
                metrics.steps_sharded = ss0
                # warmup fired-megastep payloads: sentinel watermarks
                # fire nothing, and warmup must not leave handles behind
                fire_watch.clear()
                if fire_step is not None:
                    with CompileEvents.stage("window-fire"):
                        cf = run_fire(None)
                        jax.block_until_ready(cf.counts)
                        if fire_reduced_step is not None:
                            rf = run_fire(None, reduced=True)
                            jax.block_until_ready(rf.counts)
                if env.config.get_bool("observability.compile-cost",
                                       False) \
                        and self._job_group is not None \
                        and graph is None:
                    # AOT cost_analysis of the primary update step (FLOPs
                    # / bytes accessed where the backend reports them);
                    # costs a second trace+compile, hence config-gated
                    route0 = (
                        "mask" if "mask" in steps_by_route else "exchange"
                    )
                    # the exchange route's entry is a plain wrapper; its
                    # jitted inner step rides on .jit (cost_analysis
                    # needs .lower())
                    fn0 = steps_by_route[route0]["insert"]
                    fn0 = getattr(fn0, "jit", fn0)
                    Bs = B_step[0]
                    vals0 = (
                        np.zeros(Bs, np.uint32) if red.kind == "sketch"
                        else np.zeros(
                            (Bs,) + tuple(red.value_shape), np.float32
                        )
                    )
                    # labelled: this second trace+compile must not land
                    # in the "steady" recompile-storm bucket
                    with CompileEvents.stage("cost-analysis"):
                        ca = cost_analysis_of(
                            fn0, state,
                            np.zeros(Bs, np.uint32),
                            np.zeros(Bs, np.uint32),
                            np.zeros(Bs, np.int32), vals0,
                            np.zeros(Bs, bool),
                            np.zeros(ctx.n_shards, np.int32),
                        )
                    for k, v in (ca or {}).items():
                        self._job_group.settable_gauge(
                            f"xla_update_step_{k}", v
                        )
            # re-pin the doctor's recompile baseline at setup end: the
            # labelled build bursts above and the unlabelled eager
            # warm-up shapes (device_put, init zeros) that land in the
            # process-global "steady" bucket during setup are NOT this
            # job's steady-state growth — only compiles AFTER this
            # point feed the recompile-storm rule (metrics/doctor.py)
            _doctor_steady0[0] = (
                CompileEvents.report()["by_stage"].get("steady")
                or {"count": 0, "time_ms": 0.0}
            )

        # -- checkpointing (barrier = step boundary, SURVEY §3.4) ----------
        storage = None
        if env.checkpoint_dir:
            # task-local snapshot cache (checkpointing/local.py): publish
            # mirrors in, restore prefers the verified local copy
            storage = ckpt.CheckpointStorage(
                env.checkpoint_dir,
                retain=env.config.get_int("checkpoint.retain", 2),
                local=local_cache_from_config(
                    env.config, env.checkpoint_dir
                ),
            )
        # resume numbering after any checkpoints already in the directory
        next_cid = (storage.latest() or 0) + 1 if storage else 1
        steps_at_ckpt = 0
        n_keys_logged = 0

        # -- async / incremental subsystem (flink_tpu/checkpointing) -------
        # checkpoint.mode:  full        -> every checkpoint is a
        #                                  self-contained snapshot
        #                   incremental -> delta checkpoints covering only
        #                                  the dirty key groups, chained
        #                                  to a periodic full base via
        #                                  manifest.json
        # checkpoint.async: serialize + write on a background materializer
        #                   thread; the step loop blocks only for the
        #                   staging fetch (defaults on for incremental)
        ck_mode = env.config.get_str("checkpoint.mode", "full")
        ck_compact_every = max(
            1, env.config.get_int("checkpoint.compact-every", 8)
        )
        if ck_mode == "incremental" and wagg.allowed_lateness_ms:
            # dirty bits deliberately skip the global fire/purge sweeps
            # (recovery re-applies the purge cutoff), which is exact ONLY
            # without late re-fires — see checkpointing/recovery.py
            raise ValueError(
                "checkpoint.mode=incremental does not cover allowed-"
                "lateness window stages; use checkpoint.mode=full"
            )
        # the staged-delta pipeline below writes its own files, but the
        # materializer + notify/failure protocol is the SHARED one — a
        # fourth inline copy would drift from the generic paths'
        # -- failure containment (docs/fault-tolerance.md) -----------------
        # coordinator-side budget (checkpointing/policy.py, ref
        # CheckpointFailureManager): a failed or timed-out checkpoint is
        # ABORTED and counted; only exhausting checkpoint.tolerable-
        # failures escalates to the restart strategy. The policy's
        # on_completed runs at publish time — on the materializer thread
        # in async mode — so the consecutive-failure count tracks what
        # actually became durable. (The windowed path writes through its
        # own staged-delta pipeline, so ck_io carries the policy only
        # for its bounded recover/settle/close drains.)
        ck_policy = policy_from_config(env.config) if storage is not None \
            else None
        ck_io = _GenericCheckpointIO(env, storage, pipe, policy=ck_policy)
        materializer = ck_io.materializer
        metrics.failure_budget = ck_policy
        ck_declined = [False]      # one decline counted per deferred trigger
        # checkpoint.timeout bookkeeping for async in-flight cids:
        # cid -> monotonic publish deadline. An expired cid's publish is
        # CANCELLED (the materialize closure checks before writing), so a
        # wedged write can never publish a stale cut after the budget
        # already accounted for its failure.
        ck_pending = {}
        ck_cancelled = set()
        ck_lock = threading.Lock()
        # step-loop watchdog (runtime/watchdog.py): per-phase deadlines
        # that turn a hang into an attributed failure

        def _wd_trip(trip):
            metrics.watchdog_trips += 1

        wd = watchdog_from_config(env.config, on_trip=_wd_trip)
        # MTTR instrumentation (metrics/recovery.py): per-attempt
        # recovery phase spans + recovery_* gauges + /jobs/<jid>/recovery
        rec_tracker = RecoveryTracker(self._job_group, self._tracer)
        if storage is not None and storage.local is not None:
            rec_tracker.local_cache = storage.local
        env._recovery_report = rec_tracker.report
        # warm in-process restart (docs/fault-tolerance.md): transient
        # host-side failures keep the live jitted kernels and re-stage
        # only the shards whose key groups diverged from the restored cut
        from flink_tpu.core.config import CoreOptions as _CO

        warm_enabled = env.config.get(_CO.RECOVERY_WARM_RESTART)
        # incremental cuts CLEAR the device dirty bits before their write
        # is durable; if that write later aborts, the cleared bits are
        # divergence the bits no longer show. The warm splice therefore
        # unions the live bits with every cut cleared after the cid it
        # restores (pruned once a newer cut publishes).
        ck_cleared_dirty = {}
        ck_published = [0]
        # live manifest chain of the current incremental sequence (base
        # first). Starts EMPTY even when the directory holds checkpoints:
        # a delta may only chain onto a base whose state this job actually
        # carries, so the chain is adopted exclusively by
        # restore_checkpoint — a fresh job in an old directory writes a
        # new full base instead of chaining over foreign state.
        ck_chain: List[int] = []
        # observability (metrics/core.py): phase histograms + staging
        # gauges on the job's metric group, next to the cycle histograms
        ck_hists = {}
        ck_cov_gauge = None
        if self._job_group is not None and storage is not None:
            ck_hists = {
                "sync": self._job_group.histogram("checkpoint_sync_ms"),
                "async": self._job_group.histogram("checkpoint_async_ms"),
            }
            ck_cov_gauge = self._job_group.settable_gauge(
                "checkpoint_coverage_groups", 0
            )
            if materializer is not None:
                self._job_group.gauge(
                    "checkpoint_staging_occupancy", materializer.pending
                )

        def _dump_spill_stores():
            """SYNC phase: copy the host spill-tier contents out of the
            live stores (the step loop keeps draining into them once it
            resumes, so the async fold must work on frozen copies).
            Returns [(pane, keys u64, values [n, W] f32), ...]."""
            out = []
            for p, store in ovf_stores.items():
                ks, vs = store.dump()
                if len(ks):
                    out.append((int(p), np.array(ks, copy=True),
                                np.array(vs, copy=True)))
            return out

        def _fold_spill_entries(entries, dumped):
            """Spill-tier contents ride the snapshot as regular logical
            (key, pane, value) entries; duplicates with device rows are
            pre-combined because restore scatters (last write wins)."""
            if not dumped:
                return entries
            a_hi, a_lo, a_pane, a_val = [], [], [], []
            for p, ks, vs in dumped:
                a_hi.append((ks >> np.uint64(32)).astype(np.uint32))
                a_lo.append((ks & np.uint64(0xFFFFFFFF)).astype(np.uint32))
                a_pane.append(np.full(len(ks), p, np.int32))
                a_val.append(
                    vs.reshape((len(ks),) + tuple(red.value_shape))
                )
            if not a_hi:
                return entries
            khi = np.concatenate([entries["key_hi"]] + a_hi)
            klo = np.concatenate([entries["key_lo"]] + a_lo)
            pane = np.concatenate([entries["pane"]] + a_pane)
            value = np.concatenate(
                [entries["value"].astype(np.float32)] + a_val
            )
            fresh = np.concatenate([
                entries["fresh"],
                np.zeros(len(khi) - len(entries["fresh"]), bool),
            ])
            # combine duplicate (key, pane) rows (device + spill split)
            comp = (
                (khi.astype(np.uint64) << np.uint64(32)) | klo
            ).astype(np.uint64)
            uniq, inv = np.unique(
                np.stack([comp, pane.astype(np.uint64)], 1), axis=0,
                return_inverse=True,
            )
            W = max(1, int(np.prod(red.value_shape, dtype=np.int64) or 1))
            agg = np.full((len(uniq), W), ovf_neutral, np.float32)
            ufunc.at(agg, inv, value.reshape(len(value), W))
            fr = np.zeros(len(uniq), bool)
            np.logical_or.at(fr, inv, fresh)
            return {
                "key_hi": (uniq[:, 0] >> np.uint64(32)).astype(np.uint32),
                "key_lo": (uniq[:, 0] & np.uint64(0xFFFFFFFF)).astype(
                    np.uint32
                ),
                "pane": uniq[:, 1].astype(np.int32),
                "value": agg.reshape((len(uniq),) + tuple(red.value_shape)),
                "fresh": fr,
            }

        def _abort_checkpoint(cid, err, t_ck0, trigger_ms):
            """Abort-and-count one failed checkpoint attempt (the
            containment half of the failure budget). GCs the attempt's
            staging dir; in incremental mode cancels every in-flight
            publish and RESETS the manifest chain — the failed cut's
            dirty bits are already cleared, so only a fresh full base
            can cover its changes, and no future delta may chain over
            the hole. Raises (escalating to the restart strategy) only
            when the consecutive-failure budget is exhausted."""
            storage.discard_tmp(cid)
            if ck_mode == "incremental":
                with ck_lock:
                    ck_cancelled.update(ck_pending)
                    ck_pending.clear()
                ck_chain[:] = []
            metrics.checkpoints_aborted += 1
            metrics.record_checkpoint_abort(
                cid, trigger_ms, (time.perf_counter() - t_ck0) * 1e3,
                reason=f"{type(err).__name__}: {err}",
                kind="incremental" if ck_mode == "incremental" else "full",
            )
            if ck_policy.on_aborted(cid, str(err)):
                raise ck_policy.exhausted_error(cid, err) from err

        def _expire_pending():
            """checkpoint.timeout for async in-flight checkpoints: a cid
            still unpublished past its deadline is declared failed — its
            publish is cancelled and the failure counts against the
            budget — so a wedged materialization cannot silently stall
            durability forever. timeout <= 0 disables (nothing is ever
            registered as pending then)."""
            if not ck_pending:
                return
            now = time.monotonic()
            with ck_lock:
                expired = sorted(
                    c for c, dl in ck_pending.items() if now > dl
                )
                for c in expired:
                    ck_cancelled.add(c)
                    ck_pending.pop(c, None)
            for c in expired:
                _abort_checkpoint(
                    c,
                    TimeoutError(
                        f"checkpoint {c} unpublished after "
                        f"{ck_policy.timeout_s:.0f}s (checkpoint.timeout)"
                    ),
                    time.perf_counter(), time.time() * 1000,
                )

        def write_checkpoint():
            nonlocal next_cid, steps_at_ckpt, n_keys_logged, state
            flush_fused()   # snapshot cut = megastep boundary (no-op at 1)
            t_ck0 = time.perf_counter()
            trigger_ms = time.time() * 1000
            cid = next_cid
            try:
                if materializer is not None:
                    _expire_pending()
                    # surface an async write failure AT the barrier: it
                    # is a checkpoint failure — aborted and counted (the
                    # abort record carries THIS barrier's cid; the
                    # reason names the failed chk label)
                    materializer.check()
                    ck_io.drain()
            except (JobCancelledException, WatchdogError,
                    CheckpointFailureBudgetExceeded):
                raise
            except Exception as e:
                # a poisoned materializer DROPPED its queued tasks:
                # their cids will never pop themselves from pending, and
                # none of them published — stop tracking (and block any
                # straggler publish) before counting the abort
                with ck_lock:
                    ck_cancelled.update(ck_pending)
                    ck_pending.clear()
                _abort_checkpoint(cid, e, t_ck0, trigger_ms)
                next_cid += 1
                steps_at_ckpt = metrics.steps
                return
            # drain due fires so fired_through is uniform across shards
            # and the snapshot is an exact global cut (F-throttle
            # divergence). OUTSIDE the abort scope: a sink failure while
            # emitting is a job failure, not a checkpoint failure.
            drain_fires(int(wm_strategy.current()))
            wd_prev = wd.arm("checkpoint_sync") if wd is not None else None
            try:
                _write_checkpoint_cut(cid, t_ck0, trigger_ms)
            except (JobCancelledException, WatchdogError,
                    CheckpointFailureBudgetExceeded):
                raise
            except Exception as e:
                _abort_checkpoint(cid, e, t_ck0, trigger_ms)
            finally:
                if wd is not None:
                    wd.disarm(wd_prev)
            next_cid += 1
            steps_at_ckpt = metrics.steps

        def _write_checkpoint_cut(cid, t_ck0, trigger_ms):
            nonlocal n_keys_logged, state
            # ---- SYNC phase (the only step-loop stall) -----------------
            # changelog fetch: which key groups changed since the last cut
            spill_dump = _dump_spill_stores()
            kind, dirty_kgs, rows = "full", None, None
            if ck_mode == "incremental":
                dirty_kgs = cklog.dirty_key_groups(
                    np.asarray(jax.device_get(state.kg_dirty))
                )
                # spill-tier key groups are always covered: their state
                # mutates host-side (drains/prunes) without device bits
                for _p, ks, _vs in spill_dump:
                    dirty_kgs = np.union1d(dirty_kgs, cklog.entry_key_groups(
                        (ks >> np.uint64(32)).astype(np.uint32),
                        (ks & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                        ctx.max_parallelism,
                    ))
                if ck_chain and len(ck_chain) < ck_compact_every:
                    kind = "delta"
                    rows = cklog.dirty_shard_rows(
                        dirty_kgs, *ctx.kg_bounds()
                    )
                # else: first checkpoint in the directory, or compaction
                # due -> write a fresh full base
            staged = ckpt.stage_window_state(state, rows=rows, red=red)
            if ck_mode == "incremental":
                state = clear_dirty(state)
                # cleared-bits ledger for the warm splice (see above):
                # this cut's dirty set is unaccounted divergence until
                # the cut is durable
                ck_cleared_dirty[cid] = np.asarray(dirty_kgs)
                for c in [c for c in ck_cleared_dirty
                          if c <= ck_published[0]]:
                    del ck_cleared_dirty[c]
            if keep_rev:
                # atomic against the ingest thread's concurrent encodes
                # (the map may already hold keys from prefetched batches
                # past the cut — harmless supersets on restore)
                items, n_keys_logged = codec.rev_slice(n_keys_logged)
                storage.append_keymap(items)
            aux = {
                "origin_ms": td.origin_ms,
                "wm_current": wm_strategy.current(),
                "codec_rev_count": n_keys_logged if keep_rev else 0,
                "size_ms": size_ms, "slide_ms": slide_ms,
                "lateness_ms": wagg.allowed_lateness_ms,
                "state_layout": layout[0],
                "sink_states": [s.snapshot_state() for s in pipe.all_sinks],
            }
            if graph is not None:
                # downstream stage states ride the aux blob, NOT the
                # entries npz: incremental replay merges entries by
                # (key, pane) across the manifest chain, which would
                # collide rows from different stages. The chained
                # drain's watermark coupling means a drain-boundary cut
                # carries no in-flight edge payload — these full
                # per-stage snapshots alone ARE the exactly-once cut.
                aux["chain_stages"] = graph.snapshot_chain(
                    chain_states, chain_specs
                )
            # the APPLIED-offset cut (runtime/ingest.py): the prefetch
            # thread may have polled the source several batches ahead,
            # so the snapshot names the offsets of the last batch the
            # device state has absorbed — in-flight prepped batches are
            # dropped + replayed on restore, never skipped
            offsets = ingest.applied_offsets()
            # freeze offsets/sink states NOW: the step loop resumes before
            # the write lands, and live sink state must not leak into it
            aux_bytes = pickle.dumps(
                {"source_offsets": offsets, "aux": aux}
            )
            manifest = None
            if ck_mode == "incremental":
                new_chain = ck_chain + [cid] if kind == "delta" else [cid]
                manifest = ckmf.build_manifest(
                    cid, kind, new_chain,
                    "all" if kind == "full"
                    else sorted(int(g) for g in dirty_kgs),
                    ctx.max_parallelism,
                )
                ck_chain[:] = new_chain
                if ck_cov_gauge is not None:
                    cov_n = (
                        ctx.max_parallelism if kind == "full"
                        else len(dirty_kgs)
                    )
                    ck_cov_gauge.set(cov_n)
            staging_wait = 0.0
            if materializer is not None:
                # bounded: a wedged in-flight write must surface as an
                # abortable checkpoint failure, not an unbounded stall
                # (MaterializerStall -> _abort_checkpoint)
                slot_prev = (
                    wd.arm("materializer_slot") if wd is not None else None
                )
                try:
                    staging_wait = materializer.wait_for_slot(
                        timeout=(
                            ck_policy.timeout_s
                            if ck_policy.timeout_s > 0 else None
                        )
                    )
                finally:
                    if wd is not None:
                        wd.disarm(slot_prev)
            occupancy = materializer.pending() if materializer else 0
            sync_ms = (time.perf_counter() - t_ck0) * 1e3
            if ck_hists:
                ck_hists["sync"].update(sync_ms)
            # checkpoints are rare and exactly the stalls worth seeing in
            # a trace: record regardless of the cycle sampling decision
            if tracer is not None:
                tracer.rec("checkpoint_sync", t_ck0, cid=cid, kind=kind)

            # ---- ASYNC phase (materializer thread; inline when sync) ---
            def materialize():
                try:
                    with ck_lock:
                        if cid in ck_cancelled:
                            return        # timed out: abort already counted
                    t_a0 = time.perf_counter()
                    entries, scalars = ckpt.extract_entries(staged, win)
                    entries = _fold_spill_entries(entries, spill_dump)
                    if kind == "delta":
                        entries = cklog.filter_entries_to_key_groups(
                            entries, dirty_kgs, ctx.max_parallelism
                        )
                    # last cancellation point before durability: a cut
                    # declared timed-out must never publish (its failure
                    # is already in the budget and the chain was reset)
                    with ck_lock:
                        if cid in ck_cancelled:
                            return
                    path = storage.write(
                        cid, entries, scalars,
                        manifest=manifest, aux_bytes=aux_bytes,
                    )
                    ck_policy.on_completed(cid)
                    # durable: bits cleared at or before this cut are
                    # accounted for by it (int store is GIL-atomic; the
                    # ledger itself is pruned on the step-loop thread)
                    ck_published[0] = max(ck_published[0], cid)
                    # the checkpoint is durable: commit offsets externally
                    # + let sinks finalize (ref notifyCheckpointComplete
                    # fan-out). Async mode queues — the step loop delivers.
                    if materializer is not None:
                        ck_io.queue_notification(cid, offsets)
                    else:
                        with ck_io.source_lock:
                            pipe.source.notify_checkpoint_complete(
                                cid, offsets
                            )
                        for s in pipe.all_sinks:
                            s.notify_checkpoint_complete(cid)
                    nbytes = sum(
                        os.path.getsize(os.path.join(path, f))
                        for f in os.listdir(path)
                    ) if path and os.path.isdir(path) else 0
                    async_ms = (time.perf_counter() - t_a0) * 1e3
                    if ck_hists:
                        ck_hists["async"].update(async_ms)
                    metrics.record_checkpoint(
                        cid, trigger_ms,
                        (time.perf_counter() - t_ck0) * 1e3,
                        nbytes, len(entries["key_hi"]),
                        # sync mode: the WHOLE checkpoint stalls the loop
                        kind=kind,
                        sync_ms=sync_ms if materializer is not None
                        else None,
                        async_ms=async_ms if materializer is not None
                        else 0.0,
                        coverage=(
                            None if dirty_kgs is None or kind == "full"
                            else len(dirty_kgs)
                        ),
                        staging_wait_ms=staging_wait * 1e3,
                        staging_occupancy=occupancy,
                    )
                finally:
                    with ck_lock:
                        ck_pending.pop(cid, None)

            if materializer is not None:
                if ck_policy.timeout_s > 0:     # 0/negative = no timeout
                    with ck_lock:
                        ck_pending[cid] = (
                            time.monotonic() + ck_policy.timeout_s
                        )
                try:
                    materializer.submit(f"chk-{cid}", materialize)
                except BaseException:
                    with ck_lock:      # never-queued cid must not "expire"
                        ck_pending.pop(cid, None)
                    raise
            else:
                materialize()

        def _try_warm_splice(entries, scalars, restored_cid):
            """Warm dirty-only re-stage: rebuild ONLY the shards whose
            key-group range diverged since the restored cut and splice
            them into the live device state; clean shards never leave
            the device. Sound only when the cut's fire horizon still
            matches the live state — fire/purge sweeps mutate shards
            WITHOUT marking dirty bits (deliberately, see
            ops/window_kernels.py), so any fire, purge, or ring
            rotation since the cut sends the caller down the full
            re-stage path. Returns True when the splice happened. The
            spill-tier precondition is the CALLER's (the stores are
            already closed/cleared by the time this runs)."""
            nonlocal state
            live = jax.device_get({
                "fired_through": state.fired_through,
                "max_pane": state.max_pane,
                "min_pane": state.min_pane,
                "kg_dirty": state.kg_dirty,
                "ovf_n": state.ovf_n,
            })
            if (
                int(np.min(live["fired_through"]))
                != int(scalars["fired_through"])
                or int(np.max(live["max_pane"])) != int(scalars["max_pane"])
                or int(np.min(live["min_pane"])) != int(scalars["min_pane"])
                or int(np.asarray(live["ovf_n"]).sum()) != 0
            ):
                return False
            dirty = cklog.dirty_key_groups(live["kg_dirty"])
            # plus every dirty set a post-cut checkpoint cleared without
            # becoming durable (the bits no longer show that divergence)
            for c, kgs in list(ck_cleared_dirty.items()):
                if c > restored_cid:
                    dirty = np.union1d(dirty, kgs)
            rows = cklog.dirty_shard_rows(dirty, *ctx.kg_bounds())
            if len(rows) >= ctx.n_shards:
                return False     # everything diverged: splice == full
            S = ctx.n_shards
            repl = {
                # global scalars rewind to the cut (fired_through /
                # max_pane / min_pane are equal by the guard; watermark
                # and the drop counters are re-driven by replay)
                "watermark": ckpt._scal(S, scalars["watermark"], ctx),
                "dropped_late": ckpt._scal(
                    S, scalars["dropped_late"], ctx, split=True
                ),
                "dropped_capacity": ckpt._scal(
                    S, scalars["dropped_capacity"], ctx, split=True
                ),
                # the restored state IS the chain's state
                "kg_dirty": jax.device_put(
                    np.zeros((S, ctx.max_parallelism), bool),
                    ctx.state_sharding,
                ),
            }
            if rows:
                leftover = []
                built = ckpt.restore_window_rows(
                    entries, scalars, ctx, spec, rows=rows,
                    leftover=leftover,
                )
                if leftover:
                    return False     # rows need the spill tier: full path
                idx = jnp.asarray(np.asarray(rows, np.int32))

                def spl(live_arr, sub):
                    return jax.device_put(
                        live_arr.at[idx].set(jnp.asarray(sub)),
                        ctx.state_sharding,
                    )

                repl.update(
                    table=type(state.table)(
                        spl(state.table.keys, built["keys"]),
                        spec.probe_len,
                    ),
                    fresh=spl(state.fresh, built["fresh"]),
                    pane_ids=spl(state.pane_ids, built["pane_ids"]),
                    n_fresh=spl(state.n_fresh, built["n_fresh"]),
                )
                if use_packed:
                    # restore rows are logical; re-pack before splicing
                    # onto the live packed plane (touched rides inside)
                    repl.update(acc=spl(state.acc, wk.make_packed(
                        built["acc"], built["touched"], red
                    )))
                else:
                    repl.update(
                        acc=spl(state.acc, built["acc"]),
                        touched=spl(state.touched, built["touched"]),
                    )
            # rows == []: nothing diverged since the cut — the live
            # arrays ARE the checkpoint; only the scalars rewind
            state = dataclasses.replace(state, **repl)
            return True

        def _seed_spill_leftover(leftover):
            """Snapshot rows that no longer fit the device table go back
            to the host spill tier they came from (shared by the full
            restore and the live savepoint-cut rescale — a rescale to
            FEWER shards shrinks total device capacity, so rows that fit
            at N shards may spill at M)."""
            if not leftover:
                return
            from flink_tpu.native import SpillStore

            for l_hi, l_lo, l_pane, l_val in leftover:
                k64 = (
                    l_hi.astype(np.uint64) << np.uint64(32)
                ) | l_lo.astype(np.uint64)
                for p in np.unique(l_pane):
                    m = l_pane == p
                    store = ovf_stores.get(int(p))
                    if store is None:
                        store = ovf_stores[int(p)] = SpillStore(
                            width=ovf_w, initial_capacity=1024
                        )
                    store.put(
                        k64[m],
                        l_val[m].reshape(-1, ovf_w).astype(np.float32),
                    )

        def _replan_mesh(devices):
            """Re-slice + rebuild for a NEW shard count (elastic
            degrade onto survivors, or the scale-back-up): a fresh
            MeshContext over ``devices`` (key-group ranges re-slice
            through the unchanged compute_key_group_range math — keys
            never change key group), and every mesh-derived compiled/
            cached artifact is dropped so the next setup() rebuilds the
            whole jitted step family, the exchange geometry, and the
            ingest plan at the new ``n_shards``. The caller completes
            the re-plan with a restore (rescaled cut) — state is NOT
            touched here."""
            nonlocal ctx, _kg_ends, compact_step_fn
            if (kg_slices_hold[0] is not None
                    and len(kg_slices_hold[0]) != len(devices)):
                # a heat-balanced slicing is per-shard-count evidence:
                # an elastic re-plan to a DIFFERENT count falls back to
                # the uniform slices (the controller re-derives later)
                kg_slices_hold[0] = None
            ctx = MeshContext.create(
                len(devices), env.max_parallelism, devices=devices,
                kg_slices=kg_slices_hold[0],
            )
            _kg_ends = np.asarray(ctx.kg_bounds()[1])
            steps_by_route.clear()
            megasteps_by_route.clear()
            residents_by_route.clear()
            compact_step_fn = None
            kg_occ_step_fn[0] = None
            kg_occ_cache[0] = None
            exchange_cap[0] = 0
            shard_cap[0] = 0    # re-sliced by setup() at the new n_shards
            force_route[0] = None
            # in-flight monitoring handles reference the OLD mesh (a
            # dead device on real hardware): drop without blocking
            inflight.clear()

        def _rescale_live(targets, kind: str, cause: str):
            """Planned savepoint-cut rescale at a cycle boundary — the
            scale-back-up edge that bounds degraded mode (and, by
            symmetry, any operator-triggered live re-plan). Semantics
            match write_savepoint: pending fused groups dispatch, due
            windows fire BEFORE the cut, then the logical snapshot
            (device + spill tier) re-buckets onto the new mesh and the
            source rewinds to the applied-offset cut so prefetched
            batches replay — exactly-once, no restart, no durable-
            storage round trip."""
            nonlocal state, host_fired_pane, applied_max_pane
            t0 = time.perf_counter()
            n_before = ctx.n_shards
            flush_fused()
            consume_fires(force=True)
            drain_fires(int(wm_strategy.current()), time.perf_counter())
            ingest.pause()
            fused.clear()
            fire_watch.clear()
            entries, scalars = ckpt.snapshot_window_state(state, win,
                                                          red=red)
            entries = _fold_spill_entries(entries, _dump_spill_stores())
            for store in ovf_stores.values():
                store.close()
            ovf_stores.clear()
            offsets = ingest.applied_offsets()
            # downstream stage states re-bucket over the new mesh the
            # same way: logical snapshot before the re-plan, restore
            # against the re-planned chain_specs after setup()
            ch_payload = (
                graph.snapshot_chain(chain_states, chain_specs)
                if graph is not None else None
            )
            _replan_mesh(targets)
            setup(td.origin_ms, fresh_state=False)
            leftover = [] if win.overflow else None
            state = ckpt.restore_window_state(
                entries, scalars, ctx, spec, leftover=leftover
            )
            if graph is not None:
                chain_states[:] = graph.restore_chain(
                    ch_payload, ctx, chain_specs
                )
            _seed_spill_leftover(leftover)
            # live-state divergence since the last durable cut has no
            # dirty bits anymore (the re-bucketed state restores with
            # clean bits): the next incremental checkpoint must re-base
            # full instead of chaining a delta over the hole
            ck_chain[:] = []
            host_fired_pane = -(2**62)
            applied_max_pane = (
                int(entries["pane"].max()) if len(entries["pane"])
                else None
            )
            step_mode[0] = "insert"
            tier_quiet[0] = 0
            miss_tolerance[0] = 0
            bounce_miss[0] = 0
            mon_watch.clear()
            pipe.source.restore_offsets(offsets)
            ingest.resume(offsets)
            mttr_ms = (time.perf_counter() - t0) * 1e3
            elastic_ctl.record(kind, n_before, ctx.n_shards, cause=cause,
                               mttr_ms=mttr_ms)
            rec_tracker.note_rescale(
                n_before, ctx.n_shards, elastic_ctl.degraded_shards
            )

        def restore_checkpoint(path_or_storage, cid=None, warm=False):
            nonlocal state, next_cid, steps_at_ckpt, n_keys_logged
            nonlocal host_fired_pane, applied_max_pane
            t_plan0 = time.perf_counter()
            # park the prefetch producer FIRST: everything below mutates
            # state it reads (source offsets, the codec reverse map, the
            # ingest plan); resume() at the end bumps the epoch so every
            # batch prepped before this restore is discarded + replayed
            ingest.pause()
            # pending fused batches belong to the pre-restore epoch: they
            # were never applied and never marked, so dropping them here
            # simply lets the rewound source replay them
            fused.clear()
            # unread resident-pipeline fire payloads die with the failed
            # state: the restored cut re-fires them on replay (the same
            # at-least-once sink contract as fires emitted-then-replayed)
            fire_watch.clear()
            if materializer is not None:
                ck_io.recover()           # durable cuts still notify
            with ck_lock:
                # restoring IS the recovery from any in-flight attempt:
                # whatever landed during recover()'s bounded drain is a
                # valid cut; the rest stop being tracked. ck_cancelled
                # is KEPT — a cancelled cid whose wedged write outlived
                # the drain must still never publish (cids are
                # monotonic, so stale entries can never block new ones).
                ck_pending.clear()
            host_fired_pane = -(2**62)   # re-arm boundary fire detection
            applied_max_pane = None      # re-armed from the snapshot below
            # restored table contents differ from the running population:
            # re-enter insert mode until the lagged signal proves quiet
            step_mode[0] = "insert"
            tier_quiet[0] = 0
            miss_tolerance[0] = 0
            bounce_miss[0] = 0
            mon_watch.clear()
            # spill contents were folded into the snapshot's entries; the
            # restored device state supersedes the host tier. Whether the
            # tier WAS in play decides warm-splice eligibility below:
            # spill keys' cut entries live nowhere on device, so only the
            # full restore (its leftover path) can resurrect them.
            had_spill = bool(ovf_stores)
            for store in ovf_stores.values():
                store.close()
            ovf_stores.clear()
            st = _storage_for_restore_path(storage, path_or_storage)
            cid = cid if cid is not None else st.latest()
            if cid is None:
                raise FileNotFoundError(f"no checkpoint in {st.dir}")
            rec_tracker.mark_phase("restore_plan", t_plan0)
            t_fetch0 = time.perf_counter()
            entries, scalars, offsets, aux = st.read(cid)
            rec_tracker.mark_phase("fetch", t_fetch0)
            if (aux["size_ms"], aux["slide_ms"]) != (size_ms, slide_ms):
                raise ValueError("checkpoint window spec mismatch")
            # re-arm the between-polls jump guard from the snapshot: the
            # restored ring holds unfired panes up to this id, and the
            # first post-restore batch may arrive after an arbitrary
            # event-time gap (the resume-after-gap scenario is exactly a
            # restore) — with the guard disarmed it would rotate the ring
            # over them
            if len(entries["pane"]):
                applied_max_pane = int(entries["pane"].max())
            # resume in the layout the snapshot was taken with (auto only;
            # an explicit config wins): an auto-direct run restored as
            # "hash" would upsert a dense key population into a table at
            # ~100% load factor and fail. Snapshot entries are logical, so
            # restore_window_state re-buckets them into whatever layout
            # the stage runs; pre-layout checkpoints (no key) were hash.
            if layout[0] is None:
                layout[0] = (
                    aux.get("state_layout", "hash")
                    if layout_cfg == "auto" else layout_cfg
                )
            t_stage0 = time.perf_counter()
            # warm in-process restart: the transient-failure path keeps
            # the live jitted kernels and the installed ingest plan (the
            # time-domain origin is unchanged for a same-job restore)
            # and, when the cut's fire horizon still matches, re-stages
            # only the dirty shards
            mode = "full"
            if (
                warm and warm_enabled and state is not None
                and td is not None and win is not None
                and aux["origin_ms"] == td.origin_ms
                and aux.get("state_layout", layout[0]) == layout[0]
            ):
                # a live spill tier rules out the splice (its keys' cut
                # entries exist on no device shard — only the full
                # rebuild's leftover path resurrects them) but not the
                # kernel-warm full restore
                # chained jobs always take the full re-stage: the splice
                # only re-stages stage 0's dirty shards, but the cut's
                # chain_stages snapshots replace EVERY downstream state
                # wholesale — a spliced stage 0 paired with wholesale
                # downstream restores would tear the watermark coupling
                mode = (
                    "warm-splice"
                    if not had_spill and graph is None
                    and _try_warm_splice(entries, scalars, cid)
                    else "warm-full"
                )
            leftover = None
            if mode != "warm-splice":
                if mode == "full":
                    setup(aux["origin_ms"], fresh_state=False)
                leftover = [] if win.overflow else None
                state = ckpt.restore_window_state(
                    entries, scalars, ctx, spec, leftover=leftover
                )
                if graph is not None:
                    if "chain_stages" not in aux:
                        raise ValueError(
                            "checkpoint carries no chain_stages payload "
                            "but the job is a chained stage graph — "
                            "restore with the matching pipeline"
                        )
                    chain_states[:] = graph.restore_chain(
                        aux["chain_stages"], ctx, chain_specs
                    )
                elif aux.get("chain_stages"):
                    raise ValueError(
                        "checkpoint carries chained stage state but the "
                        "job is single-stage — restore with the matching "
                        "pipeline"
                    )
            rec_tracker.mark_phase("stage", t_stage0)
            rec_tracker.set_mode(mode, cid)
            _seed_spill_leftover(leftover)
            pipe.source.restore_offsets(offsets)
            sink_states = aux.get("sink_states")
            if sink_states:
                if len(sink_states) != len(pipe.all_sinks):
                    raise ValueError(
                        f"checkpoint has {len(sink_states)} sink states but "
                        f"the job topology has {len(pipe.all_sinks)} sinks — "
                        f"restore with the matching pipeline"
                    )
                for s, ss in zip(pipe.all_sinks, sink_states):
                    s.restore_state(ss)
            wm_strategy._current = aux["wm_current"]
            count = aux.get("codec_rev_count", 0)
            if count:
                codec._rev = st.read_keymap(count)
            same_dir = storage is not None and (
                os.path.abspath(st.dir) == os.path.abspath(storage.dir)
            )
            n_keys_logged = len(codec._rev) if same_dir else 0
            if ck_mode == "incremental":
                # extend the restored checkpoint's chain; a FOREIGN
                # restore (savepoint) starts a fresh chain with a full
                # base — its members don't exist in our directory
                m = st.read_manifest(cid) if same_dir else None
                ck_chain[:] = (
                    list(m["chain"]) if m is not None
                    else [cid] if same_dir else []
                )
            steps_at_ckpt = metrics.steps
            # restart production from the rewound source; the restored
            # snapshot's offsets ARE the applied cut until the first
            # post-restore batch lands
            ingest.resume(offsets)

        def write_savepoint(path: str) -> str:
            """Manually-triggered versioned snapshot into its own directory
            (ref SavepointStore + CliFrontend ACTION_SAVEPOINT). Unlike
            periodic checkpoints, the full key map is embedded so the
            savepoint directory is self-contained.

            DOCUMENTED DIVERGENCE from the reference: windows already due
            at the current watermark are fired and emitted to the sinks
            BEFORE the snapshot (the reference's savepoint barrier
            snapshots pending fires instead). This keeps the savepoint an
            exact between-steps cut — restoring never re-fires or loses a
            due window — at the cost of output timing being advanced by a
            control-plane action."""
            if td is None:
                raise RuntimeError("no state to savepoint yet")
            sp = ckpt.CheckpointStorage(path, retain=10**9)
            flush_fused()   # savepoint cut = megastep boundary
            drain_fires(int(wm_strategy.current()))
            entries, scalars = ckpt.snapshot_window_state(state, win,
                                                          red=red)
            entries = _fold_spill_entries(entries, _dump_spill_stores())
            n_rev = 0
            if keep_rev:
                # atomic snapshot vs concurrent ingest-thread encodes
                items, n_rev = codec.rev_slice(0)
                sp.append_keymap(items)
            aux = {
                "origin_ms": td.origin_ms,
                "wm_current": wm_strategy.current(),
                "codec_rev_count": n_rev,
                "size_ms": size_ms, "slide_ms": slide_ms,
                "lateness_ms": wagg.allowed_lateness_ms,
                "state_layout": layout[0],
                "sink_states": [s.snapshot_state() for s in pipe.all_sinks],
            }
            if graph is not None:
                # same aux-not-entries placement as the periodic cut
                aux["chain_stages"] = graph.snapshot_chain(
                    chain_states, chain_specs
                )
            cid = (sp.latest() or 0) + 1
            # applied-offset cut, like periodic checkpoints: prefetched-
            # ahead batches are NOT part of the savepoint and replay on
            # restore from the rewound source position
            return sp.write(cid, entries, scalars,
                            ingest.applied_offsets(), aux)

        self._savepoint_writer = write_savepoint

        def kv_read(key):
            """Live point lookup into the device window state (queryable
            state read path, SURVEY §2.2): host-side probe of the shard's
            hash table + pane ring for the key. Returns
            {"panes": {pane_id: value}, "slide_ms", "size_ms"} or None.
            MUST run on the executor thread while the job is live: the
            window step donates the state buffers, so reading them from
            another thread races XLA's in-place reuse (round-1 bug)."""
            if td is None or state is None:
                return None
            from flink_tpu.core.keygroups import assign_to_key_group
            from flink_tpu.ops.hashing import route_hash

            hi, lo = codec.encode(
                np.asarray([key]) if np.isscalar(key) or isinstance(
                    key, (int, float)
                ) else [key],
                keep_reverse=False,
            )
            kg = int(assign_to_key_group(
                route_hash(hi, lo, np), ctx.max_parallelism, np
            )[0])
            shard = int(ctx.shard_of_key_groups(np.asarray([kg]))[0])
            tkeys = np.asarray(state.table.keys[shard])
            match = np.nonzero(
                (tkeys[:, 0] == hi[0]) & (tkeys[:, 1] == lo[0])
            )[0]
            panes = {}
            if match.size:
                slot = int(match[0])
                R = win.ring
                C_cap = tkeys.shape[0]
                acc_s = np.asarray(state.acc[shard])
                if state.packed >= 0:
                    acc_s, touched_f = wk.split_packed(
                        acc_s, state.packed, red
                    )
                    touched = np.asarray(touched_f).reshape(R, C_cap)
                else:
                    touched = np.asarray(
                        state.touched[shard]
                    ).reshape(R, C_cap)
                acc2 = acc_s.reshape((R, C_cap) + acc_s.shape[1:])
                pane_ids = np.asarray(state.pane_ids[shard])
                for r in range(R):
                    if touched[r, slot] and pane_ids[r] != wk.PANE_NONE:
                        panes[int(pane_ids[r])] = np.asarray(
                            acc2[r, slot]
                        ).tolist()
            # degraded mode: contributions for this key may live in the host
            # spill tier (table filled mid-pane, or the key was evicted by
            # compaction) — combine them so queryable state matches what a
            # fire would emit (round-2 ADVICE: spill rows were omitted).
            if ovf_stores:
                k64 = np.asarray(
                    [(np.uint64(hi[0]) << np.uint64(32)) | np.uint64(lo[0])],
                    np.uint64,
                )
                for p, store in ovf_stores.items():
                    if len(store) == 0:
                        continue
                    old, found = store.get(k64)
                    if not bool(found[0]):
                        continue
                    sv = old.reshape(1, ovf_w)
                    if p in panes:
                        dev = np.asarray(panes[p], np.float32).reshape(
                            1, ovf_w
                        )
                        panes[p] = host_combine(sv, dev).reshape(
                            tuple(red.value_shape) or ()
                        ).tolist()
                    else:
                        panes[p] = sv.reshape(
                            tuple(red.value_shape) or ()
                        ).tolist()
            if not panes:
                return None
            return {
                "panes": panes,
                "slide_ms": slide_ms,
                "size_ms": size_ms,
            }

        # -- queryable-state mailbox: queries from web/HTTP threads are
        # served by the executor thread at step boundaries (between steps
        # the donated device buffers are stable). `owner` claims in `box`
        # are GIL-atomic dict setdefaults, so a request is served exactly
        # once even when the job quiesces while a waiter is queued.
        kv_mailbox = queue.SimpleQueue()
        job_live = threading.Event()

        def kv_query(key):
            if not job_live.is_set():
                return kv_read(key)     # job quiescent: direct read is safe
            box = {}
            ev = threading.Event()
            kv_mailbox.put((key, box, ev))
            while not ev.wait(0.25):
                if not job_live.is_set():
                    if box.setdefault("owner", "waiter") == "waiter":
                        return kv_read(key)
                    ev.wait(5.0)
                    break
            if "err" in box:
                raise box["err"]
            return box.get("val")

        def drain_kv_mailbox():
            while not kv_mailbox.empty():
                key, box, ev = kv_mailbox.get()
                if box.setdefault("owner", "exec") != "exec":
                    ev.set()
                    continue
                try:
                    box["val"] = kv_read(key)
                except Exception as e:   # deliver to the querying thread
                    box["err"] = e
                ev.set()

        reg = getattr(env, "_kv_registry", None)
        if reg is not None:
            reg.register(wagg.name, kv_query)

        # cycle phase accumulators (CycleAttribution) + LatencyMarker stamp
        phase_acc = {"dispatch": 0.0, "emit": 0.0}
        last_ingest_t = [None]
        # step-loop span tracer (observability.tracing); local alias so
        # the hot path pays one load + None-check when tracing is off
        tracer = self._tracer

        # -- device-resident skew telemetry (ISSUE 2 tentpole part 2) ------
        # kg_fill_total: cumulative per-key-group record counts from the
        # SAMPLED lagged monitoring fetches (the traffic view — which
        # groups are receiving records). kg_occ_cache: per-key-group live-
        # key occupancy refreshed by the device kernel at fire boundaries
        # on a wall-clock budget (the state view — which groups hold
        # keys). Both are host numpy caches so gauges and the /keygroups
        # endpoint read them from web threads without ever touching the
        # donated device buffers.
        maxp_kg = ctx.max_parallelism
        kg_fill_total = np.zeros(maxp_kg, np.int64)
        kg_fill_sampled = [0]          # batches the fill counts cover
        kg_occ_cache = [None]          # np.int64 [maxp] or None
        kg_occ_step_fn = [None]        # lazily compiled occupancy kernel
        kg_last_refresh = [0.0]
        kg_interval_s = env.config.get_float(
            "observability.kg-stats-interval-ms", 1000.0
        ) / 1e3
        # observability.kg-stats gates the parts with a cost of their
        # own: the occupancy kernel (one compile + an O(C) sweep per
        # interval) and the sampled monitoring fetch for stages that
        # never fetch otherwise (no overflow ring). Defaults to ON
        # exactly when tracing is on — the shipping default's hot path
        # is byte-identical to before, and the fill counts still ride
        # the overflow monitoring fetch that spillable stages already
        # pay for.
        kg_stats_on = env.config.get_bool(
            "observability.kg-stats", tracer is not None
        )
        # observability.drain-stats gates the drain-interior flight
        # recorder (ISSUE 14): with it on, the resident/sharded drain
        # kernels stack per-slot DRAIN_STAT_FIELDS counters the consume
        # path unpacks LAGGED; with it off (the shipping default unless
        # tracing is on) the drains compile without any telemetry work —
        # the op-budget ledger pins the OFF variants byte-identical.
        drain_stats_on = env.config.get_bool(
            "observability.drain-stats", tracer is not None
        )
        # one-element holder (not a plain local) so the runtime
        # controller's drain-stats-cadence actuator can retune the host
        # fetch cadence live (ISSUE 19) — the device computes the
        # payload every drain either way; this only paces the keeps
        drain_stats_every = [max(1, env.config.get_int(
            "observability.drain-stats-every", 8
        ))]
        drain_telem = [None]   # DrainTelemetry; built in setup() when
        ds_skip = [0]          # the resident loop is live (payload cadence)
        # per-shard gauge high-water marks: how many labelled series the
        # last setup() registered, so a scale-down re-plan can remove
        # the stale tail (setup() resolves these at call time, like
        # `ingest` below)
        refusal_gauge_n = [0]
        drain_gauge_n = [0]

        def refresh_kg_occupancy(force: bool = False):
            """Run the per-key-group occupancy kernel and cache the host
            view. Called at fire boundaries (the loop is already syncing
            for the barrier fetch there) at most once per interval."""
            if not kg_stats_on or state is None or spec is None:
                return
            now = time.monotonic()
            if not force and now - kg_last_refresh[0] < kg_interval_s:
                return
            kg_last_refresh[0] = now
            if kg_occ_step_fn[0] is None:
                kg_occ_step_fn[0] = build_kg_occupancy_step(ctx, spec)
            span = (
                tracer.span("kg_occupancy") if tracer is not None
                else contextlib.nullcontext()
            )
            with span, CompileEvents.stage("kg-occupancy"):
                occ = np.asarray(
                    jax.device_get(kg_occ_step_fn[0](state))
                ).sum(axis=0)
            kg_occ_cache[0] = occ.astype(np.int64)

        def _top_k(arr, k):
            if arr is None or not len(arr):
                return []
            k = max(1, min(int(k), len(arr)))
            idx = np.argsort(arr)[::-1][:k]
            return [
                {"group": int(g), "count": int(arr[g])}
                for g in idx if arr[g] > 0
            ]

        def kg_report(k: int = 10) -> dict:
            return {
                "key_groups": maxp_kg,
                "n_shards": ctx.n_shards,
                "occupancy_top": _top_k(kg_occ_cache[0], k),
                "fill_top": _top_k(kg_fill_total, k),
                "fill_sampled_batches": kg_fill_sampled[0],
                "occupied_groups": (
                    int((kg_occ_cache[0] > 0).sum())
                    if kg_occ_cache[0] is not None else None
                ),
            }

        env._kg_report = kg_report

        def pipeline_report() -> dict:
            """/jobs/<jid>/pipeline body: the consolidated resident-
            pipeline health view (drain telemetry + refusals + the
            attribution verdict)."""
            dt = drain_telem[0]
            if dt is None:
                rep = {
                    "available": False,
                    "reason": "observability.drain-stats off or the "
                              "resident loop is not active",
                }
                # the tiers block does not need the recorder: tiered
                # jobs stay observable with drain-stats off
                if tier_mgr[0] is not None:
                    rep["tiers"] = tier_mgr[0].report()
                return rep
            try:
                dr = ingest.device_ring
            except NameError:
                dr = None      # scraped before the pipeline is built
            rep = dt.report(
                refusals=dr.refusals() if dr is not None else None
            )
            rep["drain_stats_every"] = drain_stats_every[0]
            if tier_mgr[0] is not None:
                rep["tiers"] = tier_mgr[0].report()
            if self._attribution is not None:
                rep["classification"] = self._attribution.classify()
            return rep

        env._pipeline_report = pipeline_report

        # CompileEvents is process-global: its "steady" bucket carries
        # every unlabelled compile since process start (other jobs,
        # eager warm-up shapes). The doctor's recompile-storm rule is
        # about growth DURING THIS JOB, so pin a job-start baseline and
        # serve the delta; setup() re-pins it after its build bursts.
        _doctor_steady0[0] = (
            CompileEvents.report()["by_stage"].get("steady")
            or {"count": 0, "time_ms": 0.0}
        )

        def doctor_report() -> dict:
            """/jobs/<jid>/doctor body: joins every telemetry plane into
            one snapshot and runs the ranked-findings rule engine over it
            (metrics/doctor.py). The snapshot and thresholds are embedded
            in the payload so ``python -m flink_tpu.doctor`` can replay
            the exact diagnosis offline."""
            if not env.config.get(_CoreOpts.DOCTOR):
                return {
                    "available": False,
                    "reason": "observability.doctor off",
                }
            from flink_tpu.metrics.doctor import diagnose

            comp = CompileEvents.report()
            steady = dict(comp["by_stage"].get("steady")
                          or {"count": 0, "time_ms": 0.0})
            steady["count"] = max(
                0, steady["count"] - _doctor_steady0[0]["count"]
            )
            steady["time_ms"] = round(max(
                0.0, steady["time_ms"] - _doctor_steady0[0]["time_ms"]
            ), 2)
            comp["by_stage"] = {**comp["by_stage"], "steady": steady}
            snapshot = {
                "pipeline": pipeline_report(),
                "metrics": {
                    f: getattr(metrics, f, 0)
                    for f in JobMetrics.GAUGE_FIELDS
                },
                "checkpoints": list(metrics.checkpoint_stats or []),
                "compile": comp,
                "fire_latency_ms": {
                    "p50": metrics.fire_latency_pct(50),
                    "p99": metrics.fire_latency_pct(99),
                },
            }
            rec_rep = getattr(env, "_recovery_report", None)
            if rec_rep is not None:
                try:
                    snapshot["recovery"] = rec_rep()
                except Exception:
                    pass
            thresholds = {
                "starved": env.config.get(
                    _CoreOpts.DOCTOR_STARVED_THRESHOLD),
                "saturated": env.config.get(
                    _CoreOpts.DOCTOR_SATURATED_THRESHOLD),
                "edge_utilization": env.config.get(
                    _CoreOpts.DOCTOR_EDGE_UTILIZATION_THRESHOLD),
                "kg_skew": env.config.get(
                    _CoreOpts.DOCTOR_KG_SKEW_THRESHOLD),
                "recompile": env.config.get(
                    _CoreOpts.DOCTOR_RECOMPILE_THRESHOLD),
                "tier_churn": env.config.get(
                    _CoreOpts.DOCTOR_TIER_CHURN_THRESHOLD),
                "tier_miss": env.config.get(
                    _CoreOpts.DOCTOR_TIER_MISS_THRESHOLD),
            }
            payload = diagnose(snapshot, thresholds)
            payload["snapshot"] = snapshot
            payload["thresholds"] = thresholds
            return payload

        env._doctor_report = doctor_report
        if self._job_group is not None:
            grp = self._job_group
            # effective fused depth of the most recent dispatch (K for a
            # megastep, 1 for single-step / partial-group flushes)
            fuse_gauge[0] = grp.settable_gauge("steps_per_dispatch", 1)
            # configured HBM batch-ring depth, 0 while the resident
            # loop is off (the resident_drains counter rides
            # JobMetrics.GAUGE_FIELDS)
            grp.gauge("ring_depth",
                      lambda: ring_depth if use_resident else 0)
            # publish-refusal backpressure (round 13): total refusals
            # across shards, plus a per-shard labelled series once the
            # ring is sharded — a stalled shard shows up here instead
            # of being inferred from throughput dips. `ingest` binds
            # later in this scope; the lambda resolves at scrape time.

            def _ring_refusals(shard=None):
                try:
                    dr = ingest.device_ring
                except NameError:
                    return 0   # scraped before the pipeline is built
                if dr is None:
                    return 0
                r = dr.refusals()
                if shard is None:
                    return int(sum(r))
                return int(r[shard]) if shard < len(r) else 0

            grp.gauge("ring_publish_refusals", _ring_refusals)
            # the per-shard labelled series registers from setup():
            # use_dp is only finalized after the resident-loop config
            # resolves, well past this point in the linear body.

            def _occ_stat(fn, default=0):
                occ = kg_occ_cache[0]
                if occ is None:
                    return default
                nz = occ[occ > 0]
                return fn(nz) if len(nz) else default

            grp.gauge("kg_occupied_groups",
                      lambda: _occ_stat(len))
            grp.gauge("kg_occupancy_max",
                      lambda: _occ_stat(lambda nz: int(nz.max())))
            grp.gauge("kg_occupancy_mean",
                      lambda: _occ_stat(
                          lambda nz: round(float(nz.mean()), 2)))
            # skew = hottest group / mean over occupied groups; 1.0 is a
            # perfectly balanced population, >> 1 is the untunable-skew
            # signal (Multicore-SSP: you cannot tune what you cannot
            # attribute)
            grp.gauge("kg_skew_ratio",
                      lambda: _occ_stat(lambda nz: round(
                          float(nz.max() / nz.mean()), 3), default=1.0))
            grp.gauge("kg_fill_max",
                      lambda: int(kg_fill_total.max(initial=0)))
            grp.gauge("kg_hot_group",
                      lambda: int(kg_fill_total.argmax())
                      if kg_fill_total.any() else -1)
            # per-stage watermark + lag gauges (tentpole part 2): how far
            # the watermark trails wall clock and the data it has seen
            grp.gauge("watermark_ms", wm_strategy.current)
            grp.gauge("watermark_lag_ms",
                      lambda: wm_strategy.watermark_lag_ms(
                          int(time.time() * 1000)))
            grp.gauge("event_time_lag_ms", wm_strategy.event_time_lag_ms)

        # Bounded step pipelining: async dispatch lets the host run ahead
        # of the device, but an UNBOUNDED queue means a pane-boundary fire
        # — and therefore every fired window's latency — waits behind the
        # whole backlog (the round-3 p99 was ~3x the reference drain's for
        # exactly this reason). Keep at most `max_inflight` update steps
        # in flight by waiting on the tiny monitoring handle from
        # `max_inflight` steps back before dispatching further: the wait
        # overlaps with the queued steps, costs nothing while the device
        # keeps up, and caps the fire wait at ~max_inflight step times.
        inflight = deque()
        max_inflight = env.config.get_int("pipeline.max-inflight-steps", 4)

        # precomputed for the per-batch adaptive route choice
        _kg_ends = np.asarray(ctx.kg_bounds()[1])

        def _pick_route(hi, lo, valid):
            """Step-loop route fallback for batches the ingest side did
            not plan (warmup, catch-up slices, chunked polls). ONE
            implementation of the exchange-feasibility math exists —
            ingest.plan_route — so prep-planned and loop-routed batches
            can never disagree on bucket fit; callers pass prefix-valid
            masks, so the valid lanes are exactly the leading
            count_nonzero lanes (matching prep's unpadded view)."""
            if force_route[0] is not None:
                return force_route[0]
            n_valid = int(np.count_nonzero(valid))
            return ingest_mod.plan_route(
                ingest.plan, hi[:n_valid], lo[:n_valid]
            )

        def _tier_args():
            # trailing residency-mask operand of every tiered kernel —
            # data, not structure: a demote/promote swaps the device
            # array, never the compiled step
            return (tier_mask_dev[0],) if use_tiers[0] else ()

        def run_update(hi, lo, ticks, values, valid, wm_ms, staged=None,
                       route=None):
            """Dispatch one update-only device step. No host sync: the
            result is not read, so transfers and compute of successive
            steps overlap (the round-1 loop blocked on every step). The
            step's tiny (ovf_n, activity) output handles are queued for
            LAGGED monitoring — inspected a few steps later when they have
            already materialized, so the pipeline never stalls. `activity`
            drives the insert<->fast step tiering (wk.update insert flag).

            `route`/`staged`: precomputed by the ingest side
            (runtime/ingest.py) — the route plan and the device-resident
            padded arrays of a prefetched batch. When the ingest plan has
            staging on, host-array calls (warmup, catch-up slices) are
            staged HERE with the same shardings, so every dispatch feeds
            the compiled step identically-committed inputs and the step
            never recompiles mid-stream."""
            nonlocal state
            wm_ticks = (
                min(int(td.to_ticks(wm_ms)), 2**31 - 4)
                if wm_ms is not None else None
            )
            # numpy, NOT jnp.full: an eager device op for this tiny vector
            # costs a full ~100ms tunnel round trip per call; as a jit
            # argument it rides the step's (queued, cheap) input transfer
            # lint: allow(retrace): deliberate tiny [n_shards] per-dispatch vector — see the comment above; hoisting would share a buffer across queued async dispatches
            wmv = np.full((ctx.n_shards,), np.int32(
                wm_ticks if wm_ticks is not None else -(2**31) + 1
            ))
            t_d0 = time.perf_counter()
            if route is None:
                route = _pick_route(hi, lo, valid)
            # route span: only a sampled-traced cycle pays the extra
            # perf_counter read between routing and dispatch
            t_r1 = (
                time.perf_counter()
                if tracer is not None and tracer.active else None
            )
            tiers = steps_by_route[route]
            tier = (
                "fast"
                if step_mode[0] == "fast" and tiers["fast"] is not None
                else "insert"
            )
            active = tiers[tier]
            if active is None:
                # chained stage graphs register route placeholders only
                # (every dispatch goes through the chained resident
                # drain); reaching here means a dispatch path missed its
                # chained branch — fail loudly, never silently drop
                raise RuntimeError(
                    f"no single-step kernel for route {route!r}: chained "
                    f"stage jobs must dispatch via the resident drain"
                )
            # chaos seam: a dying chip surfaces as a runtime error out
            # of the dispatch — the device_loss fault class injects
            # exactly there (no-op module-global check in production)
            faults.inject("step.dispatch", step=metrics.steps,
                          route=route)
            if staged is None:
                s_args, did_stage = _stage_planned(
                    (hi, lo, ticks, values, valid), route
                )
                if did_stage:
                    staged = s_args
            if staged is not None:
                state, (ovf_handle, act_handle, kgf_handle) = active(
                    state, *staged, wmv, *_tier_args(),
                )
            else:
                state, (ovf_handle, act_handle, kgf_handle) = active(
                    state, jnp.asarray(hi), jnp.asarray(lo),
                    jnp.asarray(ticks), jnp.asarray(values),
                    jnp.asarray(valid), wmv, *_tier_args(),
                )
            # dispatch normally returns immediately; it BLOCKS when the
            # device pipeline is saturated -> the device-bound signal.
            # The depth-cap wait below is part of the same device-bound
            # attribution: it only takes time when the device lags.
            inflight.append(act_handle)
            if len(inflight) > max_inflight:
                inflight.popleft().block_until_ready()
            t_d1 = time.perf_counter()
            phase_acc["dispatch"] += t_d1 - t_d0
            if t_r1 is not None:
                tracer.rec("route", t_d0, t_r1, route=route)
                tracer.rec("dispatch", t_r1, t_d1, route=route, tier=tier,
                           step=metrics.steps)
            metrics.steps += 1
            if tier == "fast":
                metrics.steps_fast += 1
            if route == "exchange":
                metrics.steps_exchanged += 1
            # SAMPLED lagged monitoring: a cold device->host fetch on
            # this runtime costs ~70ms of fixed round-trip latency
            # (async pre-copy measured even slower), so only every
            # MON_EVERY-th step's handles are retained and inspected;
            # the overflow ring is auto-sized to absorb the whole
            # detection lag (see setup()). The kg_fill skew counts ride
            # the same sampled fetch for free; observability.kg-stats
            # additionally enables it for stages with no overflow ring
            # (strict capacity / direct layout), which otherwise never
            # pay a monitoring fetch at all.
            if win.overflow or kg_stats_on:
                mon_skip[0] += 1
                if mon_skip[0] >= MON_EVERY:
                    mon_skip[0] = 0
                    mon_watch.append(
                        (ovf_handle, act_handle, kgf_handle, 1)
                    )
                    check_overflow_pressure()

        def _pad_planned(pb):
            """Pad a planned batch's host arrays to step shape: the
            5-tuple (hi, lo, ticks, values, valid) every update-step
            variant takes. The ONE copy of the padding recipe."""
            Bs = B_step[0]
            return (
                _pad(pb.hi, Bs, np.uint32),
                _pad(pb.lo, Bs, np.uint32),
                _pad(pb.ticks, Bs, np.int32),
                _pad(pb.values, Bs, pb.values.dtype),
                ingest_mod.prefix_mask(valid_tmpl[0], pb.n),
            )

        def _stage_planned(args, route):
            """Stage a padded 5-tuple with the route's committed
            shardings when the ingest plan stages (enqueue-only
            device_put — the arrays are fresh per call, so there is no
            buffer-recycle hazard). Returns (args, staged_mode)."""
            plan = ingest.plan
            if plan is not None and plan.staging:
                return (
                    ingest_mod.stage_batch_arrays(plan, route, *args),
                    True,
                )
            return args, False

        def _empty_fused_item(route):
            """One zero batch in megastep-operand form (compile warmup)."""
            if route == "sharded":
                # sharded drains consume [n_shards, cap] ring slices
                # (leading axis split across the mesh)
                shape = (ctx.n_shards, shard_cap[0])
            else:
                shape = (B_step[0],)
            vals = (
                np.zeros(shape, np.uint32) if red.kind == "sketch"
                else np.zeros(shape + tuple(red.value_shape), np.float32)
            )
            args = (np.zeros(shape, np.uint32), np.zeros(shape, np.uint32),
                    np.zeros(shape, np.int32), vals, np.zeros(shape, bool))
            args, _ = _stage_planned(args, route)
            return (args, None, None)

        def run_update_fused(route, items):
            """Dispatch ONE K-fused megastep: `items` is exactly k_fuse
            (args, wm_ms, pb) tuples of the same route and staging mode
            (the fused slot's grouping contract). A single jitted
            lax.scan applies all K batches against donated state, so the
            fixed per-dispatch cost — this function, tracing, the
            dispatch round trip — is paid once for K micro-batches. The
            monitoring handles come back with single-step shapes (the
            megastep sums/finalizes over K on device), so the lagged
            monitoring consumer is shared; the skip counter advances by
            K to keep MON_EVERY's per-MICRO-BATCH sampling cadence (and
            therefore the overflow-detection lag) unchanged."""
            nonlocal state
            t_d0 = time.perf_counter()
            t_r1 = (
                time.perf_counter()
                if tracer is not None and tracer.active else None
            )
            tiers = megasteps_by_route[route]
            tier = (
                "fast"
                if step_mode[0] == "fast" and tiers["fast"] is not None
                else "insert"
            )
            active = tiers[tier]
            # chaos seam (see run_update): device loss out of a fused
            # dispatch takes the same elastic recovery branch
            faults.inject("step.dispatch", step=metrics.steps,
                          route=route, k=k_fuse)
            flat = []
            # lint: allow(retrace): tiny [n_shards, K] watermark matrix, fresh per fused dispatch for the same reason as run_update's wmv (queued async dispatches must not share the buffer)
            wmv = np.empty((ctx.n_shards, k_fuse), np.int32)
            for i, (args, wm_ms, _pb) in enumerate(items):
                flat.extend(args)
                wmv[:, i] = np.int32(
                    min(int(td.to_ticks(wm_ms)), 2**31 - 4)
                    if wm_ms is not None else -(2**31) + 1
                )
            if getattr(active, "fused_fire", False):
                # resident pipeline: the scan fired each sub-batch under
                # its own watermark; queue the payload handles for LAGGED
                # consumption (consume_fires) — no step-loop sync here.
                # The post-scan ovf_n handle rides along: emitting a
                # window whose spill contributions still sit in the
                # DEVICE ring would lose them, so the consumer drains
                # the ring first whenever that fill is nonzero (ovf_n is
                # monotone until a host drain, so the post-scan value
                # can never under-report the fill at fire time).
                state, (ovf_handle, act_handle, kgf_handle), fires = \
                    active(state, *flat, wmv, *_tier_args())
                # no drain-stats lane on megasteps (resident drains only)
                fire_watch.append(
                    (fires, ovf_handle, time.perf_counter(), None)
                )
                metrics.fused_fire_dispatches += 1
            else:
                state, (ovf_handle, act_handle, kgf_handle) = active(
                    state, *flat, wmv, *_tier_args(),
                )
            inflight.append(act_handle)
            if len(inflight) > max_inflight:
                inflight.popleft().block_until_ready()
            t_d1 = time.perf_counter()
            phase_acc["dispatch"] += t_d1 - t_d0
            if t_r1 is not None:
                tracer.rec("dispatch", t_r1, t_d1, route=route, tier=tier,
                           step=metrics.steps, k=k_fuse)
            metrics.steps += k_fuse
            metrics.fused_dispatches += 1
            if tier == "fast":
                metrics.steps_fast += k_fuse
            if route == "exchange":
                metrics.steps_exchanged += k_fuse
            if fuse_gauge[0] is not None:
                fuse_gauge[0].set(k_fuse)
            if win.overflow or kg_stats_on:
                mon_skip[0] += k_fuse
                if mon_skip[0] >= MON_EVERY:
                    mon_skip[0] = 0
                    # a megastep's kg_fill handle sums K batches' counts:
                    # carry K so the sampled-batch denominator stays per
                    # micro-batch
                    mon_watch.append(
                        (ovf_handle, act_handle, kgf_handle, k_fuse)
                    )
                    check_overflow_pressure()

        def run_update_resident(route, items):
            """Dispatch ONE resident ring drain: `items` is 1..ring_depth
            (args, wm_ms, pb) tuples of the same route, all device-staged
            (the drain group's contract). A single count-gated jitted
            scan applies + fires every live slot against donated state —
            slots past the count cost only the scalar predicate — so the
            fixed per-dispatch cost is paid once per ring drain at ANY
            fill level, with no per-fill recompile. Exit policy (ring
            empty, fire high-water, monitoring cadence, checkpoint cut)
            is host-side COUNT policy: whatever bounded this group's
            accumulation decides what the device consumes; slots past a
            cut simply stay in the ring for the next drain."""
            nonlocal state
            count = len(items)
            t_d0 = time.perf_counter()
            t_r1 = (
                time.perf_counter()
                if tracer is not None and tracer.active else None
            )
            tiers = residents_by_route[route]
            tier = (
                "fast"
                if step_mode[0] == "fast" and tiers["fast"] is not None
                else "insert"
            )
            active = tiers[tier]
            # chaos seam (see run_update): device loss / crash out of a
            # drain dispatch — the mid-drain exactly-once test injects
            # exactly here. Warmup drains are exempt: they dispatch
            # synthetic empty batches (already excluded from the step
            # counters), and counting them would make a rule's
            # occurrence index depend on which kernel tiers got built
            if not drain_warmup[0]:
                faults.inject("step.drain", step=metrics.steps,
                              route=route, slots=count)
                # the drain IS the steady-state dispatch: a dying chip
                # surfaces here, so the device_loss fault class
                # (step.dispatch) must be able to target resident jobs
                faults.inject("step.dispatch", step=metrics.steps,
                              route=route, slots=count)
            is_while = getattr(active, "while_drain", False)
            # the kernel's slot depth: ring depth for the scan drains,
            # the while-drain bound for while mode (the exchange scan is
            # also built at the bound there, so groups up to the bound
            # always fit whatever kernel serves the route)
            depth_k = int(getattr(active, "ring_depth", ring_depth))
            flat = []
            # lint: allow(retrace): tiny [n_shards, D] watermark matrix, fresh per drain dispatch for the same reason as run_update's wmv (queued async dispatches must not share the buffer)
            wmv = np.empty((ctx.n_shards, depth_k), np.int32)
            for i, (args, wm_ms, _pb) in enumerate(items):
                flat.extend(args)
                wmv[:, i] = np.int32(
                    min(int(td.to_ticks(wm_ms)), 2**31 - 4)
                    if wm_ms is not None else -(2**31) + 1
                )
            # pad the operand list to the kernel depth by repeating the
            # last slot: the skip branch never applies them, and the
            # MIN-sentinel watermark fires nothing even if it did — the
            # pad exists only so the scan's stacked xs keep one static
            # shape (the while drain's staged clamp plays the same role)
            for i in range(count, depth_k):
                flat.extend(items[-1][0])
                wmv[:, i] = np.int32(-(2**31) + 1)
            wd_prev = None
            if wd is not None:
                # deadline scales with the work actually handed to the
                # device: per-slot seconds x slots consumed. A sharded
                # drain retires every shard's slots concurrently — free
                # on real chips, but on the CPU backend the virtual
                # shards contend for the same host cores, so the
                # legitimate wall time grows ~n_shards x and the arm
                # must too (a deep 8-shard drain would otherwise trip a
                # deadline tuned for one chip's slots)
                # the while drain may legitimately retire MORE slots
                # than the host packed (cursor stores landing mid-drain
                # on an aliasing runtime), so its deadline arms at the
                # per-dispatch BOUND, not the observed fill — the bound
                # is what makes "one while dispatch" a well-defined unit
                # of work for the watchdog to time
                wd_scale = depth_k if is_while else count
                if (getattr(active, "sharded_drain", False)
                        and jax.default_backend() == "cpu"):
                    wd_scale = wd_scale * ctx.n_shards
                wd_prev = wd.arm("device-drain",
                                 detail=f"slots={count}", scale=wd_scale)
            try:
                # resident drains always fire in-scan: queue the payload
                # handles for LAGGED consumption (consume_fires); the
                # post-scan ovf_n handle rides along as in
                # run_update_fused
                # sharded drain kernels gate per shard: a uniform count
                # vector here (every publish fills one slot per shard,
                # possibly with an empty valid mask), but the kernel
                # contract keeps the vector so a future skew-aware ring
                # can under-fill individual shards without recompiling
                cnt = (
                    np.full(ctx.n_shards, count, np.int32)
                    if getattr(active, "sharded_drain", False)
                    else np.int32(count)
                )
                if getattr(active, "chained_drain", False):
                    # chained drain: donated state is the TUPLE of every
                    # stage's state; fires are the FINAL stage's
                    res = active(
                        (state,) + tuple(chain_states), *flat, wmv, cnt
                    )
                    sts = res[0]
                    state = sts[0]
                    chain_states[:] = sts[1:]
                    (ovf_handle, act_handle, kgf_handle), fires = \
                        res[1], res[2]
                elif is_while:
                    # while drain: the count operand becomes (cursor,
                    # base, staged). The cursor is the ring's live HBM
                    # slot (donated — the kernel reuses its buffer for
                    # the consumed count, and on an aliasing runtime the
                    # donation is what lets a mid-drain commit store be
                    # observed); base anchors it so cursor - base equals
                    # this group's fill at dispatch; staged clamps the
                    # trip count to the payloads actually packed above
                    dr = ingest.device_ring
                    # the ring cursor only fits a kernel of the SAME
                    # layout (scalar slot vs per-shard vector) — a dp
                    # job's mask-route fallback drain synthesizes a
                    # frozen cursor instead (== scan count gating)
                    cur = (
                        dr.device_cursor()
                        if dr is not None and dr.sharded
                        == bool(getattr(active, "sharded_drain", False))
                        else None
                    )
                    if getattr(active, "sharded_drain", False):
                        staged_op = np.full(ctx.n_shards, count, np.int32)
                        if cur is None:
                            cursor_op = np.full(
                                ctx.n_shards, count, np.int32)
                            base_op = np.zeros(ctx.n_shards, np.int32)
                        else:
                            cursor_op, snap = cur
                            base_op = (
                                np.asarray(snap, np.int32)
                                - np.int32(count)
                            )
                    else:
                        staged_op = np.int32(count)
                        if cur is None:
                            cursor_op = np.full(1, count, np.int32)
                            base_op = np.int32(0)
                        else:
                            cursor_op, snap = cur
                            base_op = np.int32(snap - count)
                    res = active(state, *flat, wmv, cursor_op, base_op,
                                 staged_op, *_tier_args())
                    if dr is not None and cur is not None:
                        # the dispatch consumed (donated) the grabbed
                        # cursor array; stand up a fresh one so a quiet
                        # stream's next drain never re-passes a deleted
                        # buffer
                        dr.refresh_device_cursor()
                    # res[3] is the consumed count — the host already
                    # knows the release boundary (the packed items'
                    # ring seqs; staged clamps the kernel to exactly
                    # them), so the handle is dropped, never synced
                    state, (ovf_handle, act_handle, kgf_handle), fires = \
                        res[:3]
                else:
                    res = active(state, *flat, wmv, cnt, *_tier_args())
                    # telemetry-ON drains return a 4th element: the
                    # [n_shards, D, len(DRAIN_STAT_FIELDS)] flight-
                    # recorder payload. Its handle is kept every
                    # drain-stats-every-th drain only (the device
                    # computes it every drain; the host fetch cadence is
                    # the knob) and rides the lagged fire_watch channel
                    # — never a fresh sync
                    state, (ovf_handle, act_handle, kgf_handle), fires = \
                        res[:3]
                ds_h = None
                if drain_stats_on:
                    ds_skip[0] += 1
                    if ds_skip[0] >= drain_stats_every[0]:
                        ds_skip[0] = 0
                        # while drains slot the consumed count at res[3],
                        # so their recorder payload rides one later
                        ds_h = res[4] if is_while else res[3]
                fire_watch.append(
                    (fires, ovf_handle, time.perf_counter(), ds_h)
                )
                inflight.append(act_handle)
                if len(inflight) > max_inflight:
                    inflight.popleft().block_until_ready()
            finally:
                if wd is not None:
                    wd.disarm(wd_prev)
            t_d1 = time.perf_counter()
            phase_acc["dispatch"] += t_d1 - t_d0
            if t_r1 is not None:
                tracer.rec("drain", t_r1, t_d1, route=route, tier=tier,
                           step=metrics.steps, slots=count,
                           ring_depth=ring_depth)
            metrics.steps += count
            metrics.resident_drains += 1
            metrics.fused_fire_dispatches += 1
            if tier == "fast":
                metrics.steps_fast += count
            if route == "exchange":
                metrics.steps_exchanged += count
            elif route == "sharded":
                metrics.steps_sharded += count
            if fuse_gauge[0] is not None:
                fuse_gauge[0].set(count)
            if win.overflow or kg_stats_on:
                mon_skip[0] += count
                if mon_skip[0] >= MON_EVERY:
                    mon_skip[0] = 0
                    # the drain's kg_fill handle sums `count` batches'
                    # counts — carry the batch count so the sampled
                    # denominator stays per micro-batch
                    mon_watch.append(
                        (ovf_handle, act_handle, kgf_handle, count)
                    )
                    check_overflow_pressure()

        def flush_fused():
            """Dispatch whatever the fused slot holds: a full group as
            one megastep, a partial group as sequential single steps
            (bit-identical by construction — the scan body IS the single
            step), then mark the LAST batch's offsets applied. That mark
            is the megastep-boundary checkpoint cut: a snapshot taken
            after this flush names offsets whose every prior record the
            device state has absorbed, so exactly-once is preserved with
            fusion on.

            Resident-pipeline mode (fused.hold_fires): groups are no
            longer broken at fire boundaries, so this flush also OWNS
            the crossing bookkeeping — a full group's crossings fired
            in-scan (host_fired_pane catches up here, and a modeled
            lane-backlog overrun falls back to the split drain), while a
            partial group dispatched as singles still needs the split
            drain for any crossing it carried."""
            if not len(fused):
                return
            route, staged_mode, items = fused.drain()
            # resident loop: a STAGED group of any fill 1..ring_depth is
            # one count-gated drain dispatch — partial groups no longer
            # fall back to sequential singles
            resident_ok = (
                use_resident and staged_mode
                and route in residents_by_route
            )
            full = len(items) == k_fuse
            if resident_ok:
                run_update_resident(route, items)
            elif full and route in megasteps_by_route:
                run_update_fused(route, items)
            elif staged_mode:
                for args, wm_ms, _pb in items:
                    run_update(None, None, None, None, None, wm_ms,
                               staged=args, route=route)
                if fuse_gauge[0] is not None:
                    fuse_gauge[0].set(1)
            else:
                for args, wm_ms, _pb in items:
                    run_update(*args, wm_ms, route=route)
                if fuse_gauge[0] is not None:
                    fuse_gauge[0].set(1)
            last_pb = items[-1][2]
            if last_pb is not None:
                ingest.mark_applied(last_pb)
            if resident_ok:
                # ring-drain exactly-once boundary: the drain has been
                # dispatched for every slot in this group, and the
                # offsets cut above names it — retire the HBM ring
                # slots so the prefetch thread can recycle them (the
                # async runtime keeps the buffers alive until the
                # queued drain has consumed them)
                dr = ingest.device_ring
                released = None
                if dr is not None and dr.sharded:
                    # per-shard applied cut: each shard retires through
                    # ITS highest released sequence (a refused lane's
                    # None simply leaves that shard's cursor alone), so
                    # one slow shard never pins the others' slots
                    nsh = len(dr.refusals())
                    cut = [None] * nsh
                    for it in items:
                        pb = it[2]
                        if pb is None or pb.ring_seqs is None:
                            continue
                        for s, sq in enumerate(pb.ring_seqs):
                            if sq is not None and (
                                cut[s] is None or sq > cut[s]
                            ):
                                cut[s] = sq
                    if any(sq is not None for sq in cut):
                        dr.release_shards(cut)
                    released = cut
                elif dr is not None:
                    seqs = [
                        it[2].ring_seq for it in items
                        if it[2] is not None and it[2].ring_seq is not None
                    ]
                    if seqs:
                        dr.release_through(max(seqs))
                    released = [max(seqs) if seqs else None]
                dt = drain_telem[0]
                if dt is not None and dr is not None:
                    # flight-recorder tick: absorb the ring's publish-
                    # time stamps (bounded deque swaps — no device
                    # traffic) and record this drain's duty-cycle /
                    # occupancy / publish-to-consume samples
                    if not dr.stats_enabled:
                        dr.stats_enabled = True
                    dt.ingest_publish(dr.publish_samples())
                    fills = dr.occupancy_shards()
                    dt.on_drain(
                        [len(items)] * len(fills), fills,
                        released if released is not None
                        else [None] * len(fills),
                    )
            if fused.hold_fires:
                fired_in_scan = resident_ok or (full and getattr(
                    megasteps_by_route.get(route, {}).get("insert"),
                    "fused_fire", False,
                ))
                _fused_fire_bookkeep(items, fired_in_scan)
                # lagged payload consumption: by now the PREVIOUS
                # group's fires have long materialized on device
                consume_fires()

        def _fused_fire_bookkeep(items, fired_in_scan):
            """Track pane crossings through a resident-pipeline flush.

            A full fired-megastep group emitted every due window IN the
            scan (up to F lanes per sub-step, leftovers rolling to the
            next sub-step); the host models that lane budget and only
            falls back to the split drain when the model says dues could
            have outrun the lanes (or the group was dispatched split —
            partial flush — with a crossing pending). Also catches
            host_fired_pane up to the group's last watermark, and drains
            eagerly with allowed lateness (re-fire backlogs are data-
            dependent, which the host cannot see)."""
            nonlocal host_fired_pane
            F_on = win.fires_per_step
            # device dues per advance are bounded by the ring span plus
            # the window's pane count (fire-lane plan), so a fresh job's
            # sentinel host_fired_pane cannot fake an unbounded backlog
            cap = win.ring + win.size_ticks // win.slide_ticks
            backlog = 0
            prev = host_fired_pane
            last_wm = None
            crossed = False
            for _args, wm_ms, _pb in items:
                if wm_ms is None:
                    continue
                last_wm = wm_ms
                wp = wm_pane_of(wm_ms)
                if wp > prev:
                    crossed = True
                    backlog += min(wp - prev, cap)
                    prev = wp
                if fired_in_scan:
                    backlog = max(0, backlog - F_on)
            if last_wm is None:
                return
            host_fired_pane = max(host_fired_pane, prev)
            need_split_drain = (
                backlog > 0
                or (not fired_in_scan and (crossed or eager_fire))
                or (eager_fire and fired_in_scan)
            )
            if need_split_drain:
                drain_fires(last_wm, time.perf_counter())

        def run_fire(wm_ms, reduced: bool = False):
            nonlocal state
            wm_ticks = (
                min(int(td.to_ticks(wm_ms)), 2**31 - 4)
                if wm_ms is not None else None
            )
            wmv = np.full((ctx.n_shards,), np.int32(   # numpy: see run_update
                wm_ticks if wm_ticks is not None else -(2**31) + 1
            ))
            active = fire_reduced_step if reduced else fire_step
            state, cf = active(state, wmv)
            return cf

        # -- spill tier: overflow-ring drain + host pane stores ------------
        # Records whose key found no table slot land in the device overflow
        # ring; at fire boundaries the host drains the ring into per-pane
        # native SpillStores (the RocksDB-analog tier, SURVEY §2.10 item 2 /
        # RocksDBKeyedStateBackend.java:82), compacts the device table to
        # free dead-key slots, and merges spill contributions into window
        # emissions. State capacity overruns therefore degrade to host
        # memory instead of failing the job.
        ovf_stores = {}          # pane -> native SpillStore
        compact_step_fn = None
        ovf_w = max(1, int(np.prod(red.value_shape, dtype=np.int64) or 1))
        # single host-side dispatch table for the builtin reduce kinds the
        # spill tier supports: (accumulating ufunc, neutral element)
        ufunc, ovf_neutral = _HOST_REDUCE.get(red.kind, (None, None))
        # lagged + sampled ring monitoring: every MON_EVERY-th step's
        # (ovf_n, activity) handles are retained; the oldest is inspected
        # once OVF_LAG newer samples exist — by then its step has long
        # finished, so the read is one settled round trip, amortized to
        # ~1/MON_EVERY of the fixed d2h latency per step
        mon_watch = deque()
        mon_skip = [0]
        MON_EVERY = 8
        OVF_LAG = 1

        def _absorb_kg(kgf_h, n_batches):
            """Fold one sampled dispatch's per-key-group record counts
            ([n_shards, maxp] — shards are disjoint, sum them;
            [n_shards, 0] when the steps were built without kg_fill)
            into the skew telemetry. n_batches = micro-batches the
            handle covers (K for a fused megastep), so fill-per-sampled-
            batch stays a per-batch rate."""
            kgf = np.asarray(kgf_h)
            if not kgf.size:
                return
            kg_sum = kgf.sum(axis=0)
            kg_fill_total[:] += kg_sum
            kg_fill_sampled[0] += n_batches
            # key-group heat (ISSUE 17): the same sampled fill
            # vector folds into the flight recorder's EWMA heat +
            # recency series — the demote/prefetch and
            # live-rebalance sensor; host numpy on the fetched
            # lagged handle, no extra sync
            dt_kg = drain_telem[0]
            if dt_kg is not None:
                dt_kg.absorb_kg_fill(kg_sum, n_batches)
            if tier_mgr[0] is not None:
                # tier fault accounting rides the SAME sampled
                # vector: traffic into a non-resident group = a
                # batch that fell down the route ladder (documented
                # sampled, like every MON_EVERY-cadence counter)
                tier_mgr[0].note_sample(kg_sum)

        def salvage_kg_watch():
            """Drain mon_watch keeping ONLY the kg_fill counts. The
            queued ring-fill handles go stale across an overflow drain
            (they reflect pre-drain occupancy), but the kg counts
            measure the sampled dispatch's record traffic — still valid.
            Dropping them too blinds the heat plane exactly while the
            pipeline sits in sustained overflow, which is when the
            skew sensor (tier placement, live rebalance) is the only
            thing that can relieve the pressure."""
            while mon_watch:
                _, _, kgf_h, n_batches = mon_watch.popleft()
                _absorb_kg(kgf_h, n_batches)

        def check_overflow_pressure():
            if len(mon_watch) <= OVF_LAG:
                return
            ovf_h, act_h, kgf_h, n_batches = mon_watch.popleft()
            fill = int(np.asarray(ovf_h).max(initial=0))
            act = int(np.asarray(act_h).sum())
            _absorb_kg(kgf_h, n_batches)
            # -- adaptive step tiering: while new keys are being PLACED,
            # run the upsert step; once placement stops
            # (TIER_QUIET_CHECKS consecutive zero-activity checks), switch
            # to the lookup-only fast step (~6x cheaper). A miss in fast
            # mode flips back: a missed key that insert CAN place recurs
            # as a miss on every subsequent batch, so leaving it on the
            # spill tier compounds into expensive ring drains — bouncing
            # to insert mode heals it permanently. A bounce that places
            # NOTHING proves the missing keys are chain-exhausted (insert
            # can never help); their miss level becomes the fast-mode
            # tolerance so an over-capacity residue settles in fast mode
            # instead of oscillating.
            has_fast = any(
                t["fast"] is not None for t in steps_by_route.values()
            )
            if has_fast:
                if step_mode[0] == "insert":
                    if act == 0:
                        tier_quiet[0] += 1
                        if tier_quiet[0] >= TIER_QUIET_CHECKS:
                            step_mode[0] = "fast"
                            if bounce_miss[0] and not bounce_placed[0]:
                                miss_tolerance[0] = max(
                                    miss_tolerance[0], bounce_miss[0]
                                )
                            bounce_miss[0] = 0
                    else:
                        tier_quiet[0] = 0
                        bounce_placed[0] = True
                elif act > miss_tolerance[0]:
                    step_mode[0] = "insert"
                    tier_quiet[0] = 0
                    bounce_miss[0] = act
                    bounce_placed[0] = False
            if fill > max(1, B // 8):
                # meaningful pressure: drain NOW rather than waiting for
                # the next pane boundary. The auto-sized ring (~6*B lanes)
                # absorbs the <= (OVF_LAG+1) steps of lag, so nothing is
                # lost; the sync + compaction is the degraded-mode price.
                drain_overflow()

        def host_combine(a, b):
            return ufunc(a, b)

        def _merge_ring_into_stores():
            """One pass: fetch + clear the device ring into pane stores.
            Returns True if anything was drained."""
            nonlocal state
            counts = np.asarray(jax.device_get(state.ovf_n))   # [S]
            if counts.max(initial=0) <= 0:
                return False
            slices = []
            for s in range(ctx.n_shards):
                n = int(counts[s])
                if n:
                    slices.append((state.ovf_hi[s, :n], state.ovf_lo[s, :n],
                                   state.ovf_pane[s, :n], state.ovf_val[s, :n]))
            fetched = jax.device_get(slices)
            hi = np.concatenate([f[0] for f in fetched])
            lo = np.concatenate([f[1] for f in fetched])
            panes = np.concatenate([f[2] for f in fetched])
            vals = np.concatenate([f[3] for f in fetched]).reshape(-1, ovf_w)
            k64 = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(
                np.uint64
            )
            from flink_tpu.native import SpillStore

            for p in np.unique(panes):
                sel = panes == p
                uk, inv = np.unique(k64[sel], return_inverse=True)
                agg = np.full((len(uk), ovf_w), ovf_neutral, np.float32)
                ufunc.at(agg, inv, vals[sel].astype(np.float32))
                store = ovf_stores.get(int(p))
                if store is None:
                    store = ovf_stores[int(p)] = SpillStore(
                        width=ovf_w, initial_capacity=1024
                    )
                old, found = store.get(uk)
                merged = np.where(found[:, None], host_combine(old, agg), agg)
                store.put(uk, merged)
            if tier_mgr[0] is not None:
                # pending-pane index for the prefetcher: every ring lane
                # that just folded cold is a (key-group, pane) the
                # watermark will eventually fire
                tier_mgr[0].note_cold(
                    tiers_mod.entries_key_groups(
                        {"key_hi": hi, "key_lo": lo}, ctx.max_parallelism
                    ),
                    panes,
                )
            state = clear_overflow(state)
            return True

        def drain_overflow():
            """Drain the device overflow ring into the host pane stores and
            compact the table to make room. Compaction can itself evict
            non-refitting keys' state INTO the just-cleared ring, so a
            second merge pass picks those up before any emission."""
            nonlocal state, compact_step_fn
            if win is None or not win.overflow or state is None:
                return
            if not _merge_ring_into_stores():
                return
            salvage_kg_watch()    # fill handles reflect pre-drain fill;
            #                       the kg traffic counts stay valid
            miss_tolerance[0] = 0  # compaction may change placeability
            if spec.layout == "direct":
                # no dead slots to free (slot == key, table immutable) —
                # and a hash rebuild would destroy the identity rows
                return
            # free dead-key slots so future records fit (RocksDB-compaction
            # analog); compiled lazily — overflow is the rare path
            if compact_step_fn is None:
                compact_step_fn = build_compact_step(ctx, spec)
            state = compact_step_fn(state)
            _merge_ring_into_stores()   # compaction evictees

        def spill_window_contrib(end_pane: int):
            """Combined spill contributions for the window ending at pane
            end_pane (composes its k panes). Returns (keys u64 SORTED
            unique, values [n, W] float32) — empty arrays when none."""
            k = win.panes_per_window
            ks_l, vs_l = [], []
            for q in range(end_pane - k + 1, end_pane + 1):
                store = ovf_stores.get(q)
                if store is None or len(store) == 0:
                    continue
                ks, vs = store.dump()
                ks_l.append(ks)
                vs_l.append(vs)
            if not ks_l:
                return (np.zeros(0, np.uint64),
                        np.zeros((0, ovf_w), np.float32))
            ks = np.concatenate(ks_l)
            vs = np.concatenate(vs_l)
            uk, inv = np.unique(ks, return_inverse=True)
            agg = np.full((len(uk), ovf_w), ovf_neutral, np.float32)
            ufunc.at(agg, inv, vs)
            return uk, agg

        def prune_stores(wm_ms):
            """Drop pane stores past the same horizon the device purges:
            every containing window fired AND the lateness horizon passed."""
            if not ovf_stores:
                return
            k = win.panes_per_window
            wm_ticks = min(int(td.to_ticks(wm_ms)), 2**31 - 4)
            base = max(
                wm_ticks - win.lateness_ticks,
                -(2**31) + 1 + win.slide_ticks,
            )
            wm_pane_l = (base + 1 - win.slide_ticks) // win.slide_ticks
            cutoff = min(host_fired_pane, wm_pane_l)
            for q in [q for q in ovf_stores if q + k - 1 <= cutoff]:
                ovf_stores.pop(q).close()
            if tier_mgr[0] is not None:
                # same horizon for the prefetcher's pending-pane index
                tier_mgr[0].prune_cold(cutoff - k + 1)

        def _apply_tier_plan(plan):
            """Demote/promote swap at the exactly-once cut: move the
            affected key-groups' logical entries between device slot
            rows and host pane stores, then re-splice each touched
            shard in place (the warm-restore splice machinery).
            Correctness is residency-INVARIANT — a (key, pane)'s
            pending state may legally split across both tiers (the
            mid-pane-fill overflow path already does) and fire/
            checkpoint/restore compose the halves — so the swap is
            purely a placement action; a crash anywhere inside it
            restores bit-exact from the last cut. The one ordering
            obligation: pending fire payloads were computed against
            the CURRENT placement, so they are consumed before any
            entry moves (a window must never merge the same entry
            from both tiers)."""
            nonlocal state
            tm = tier_mgr[0]
            by_shard = {}
            for g in plan.demote:
                by_shard.setdefault(tm.shard_of(g), ([], []))[0].append(g)
            for g in plan.promote:
                by_shard.setdefault(tm.shard_of(g), ([], []))[1].append(g)
            if by_shard:
                flush_fused()
                consume_fires(force=True)
                _merge_ring_into_stores()
                from flink_tpu.native import SpillStore

                def mk_store():
                    return SpillStore(width=ovf_w, initial_capacity=1024)

                def fold_cold(ent, fault_point):
                    tiers_mod.fold_entries(
                        ent, ovf_stores, ovf_w, ufunc, ovf_neutral,
                        mk_store, host_combine, fault_point=fault_point,
                    )
                    if len(ent["pane"]):
                        tm.note_cold(
                            tiers_mod.entries_key_groups(
                                ent, ctx.max_parallelism
                            ),
                            ent["pane"],
                        )

                def splice_shard(s_row, built):
                    nonlocal state
                    idx = jnp.asarray(np.asarray([s_row], np.int32))

                    def spl(live_arr, sub):
                        return jax.device_put(
                            live_arr.at[idx].set(jnp.asarray(sub)),
                            ctx.state_sharding,
                        )

                    repl = dict(
                        table=type(state.table)(
                            spl(state.table.keys, built["keys"]),
                            spec.probe_len,
                        ),
                        fresh=spl(state.fresh, built["fresh"]),
                        pane_ids=spl(state.pane_ids, built["pane_ids"]),
                        n_fresh=spl(state.n_fresh, built["n_fresh"]),
                    )
                    if use_packed:
                        # splice rows are logical; re-pack onto the live
                        # packed plane (touched rides inside)
                        repl["acc"] = spl(state.acc, wk.make_packed(
                            built["acc"], built["touched"], red
                        ))
                    else:
                        repl["acc"] = spl(state.acc, built["acc"])
                        repl["touched"] = spl(
                            state.touched, built["touched"]
                        )
                    state = dataclasses.replace(state, **repl)

                max_pane_h = np.asarray(jax.device_get(state.max_pane))
                kg_dirty_h = np.asarray(
                    jax.device_get(state.kg_dirty)
                ).copy()
                for s in sorted(by_shard):
                    dem, pro = by_shard[s]
                    staged = ckpt.stage_window_state(
                        state, rows=[s], red=red
                    )
                    # label ring rows from THIS shard's own pane clock:
                    # the staged scalars aggregate the GLOBAL max, which
                    # would mislabel a lagging shard's rows
                    staged["scalars"]["max_pane"] = int(max_pane_h[s])
                    entries, scalars = ckpt.extract_entries(staged, win)
                    kgs = tiers_mod.entries_key_groups(
                        entries, ctx.max_parallelism
                    )
                    dem_m = (
                        np.isin(kgs, np.asarray(dem, np.int64))
                        if dem else np.zeros(len(kgs), bool)
                    )
                    merged, demoted = tiers_mod.split_entries(
                        entries, ~dem_m
                    )
                    # unconditional: the demote seam fires once per
                    # shard swap even when no entries move, so chaos
                    # tests can land a crash on every swap
                    fold_cold(demoted, "tier.demote.write")
                    for g in pro:
                        got = tiers_mod.fetch_group_entries(
                            ovf_stores, g, ctx.max_parallelism, ovf_w,
                            staged["value_tail"], staged["value_dtype"],
                        )
                        tm.forget_cold(g)
                        on, off = tiers_mod.ring_window(
                            got, int(scalars["max_pane"]), int(win.ring)
                        )
                        # panes outside the live ring have no device row
                        # to hold them yet: straight back to the stores
                        # (combine-aware, never dropped); they merge at
                        # fire the normal spill way
                        fold_cold(off, None)
                        merged = tiers_mod.concat_entries(merged, on)
                    merged = tiers_mod.precombine_entries(
                        merged, ovf_w, ufunc, ovf_neutral
                    )
                    leftover = []
                    built = ckpt.restore_window_rows(
                        merged, scalars, ctx, spec, rows=[s],
                        leftover=leftover,
                    )
                    splice_shard(s, built)
                    for l_hi, l_lo, l_pane, l_val in leftover:
                        # promoted rows the table cannot place (chain
                        # exhaustion under the promote's extra keys) go
                        # straight back cold — fold, not put: a raw put
                        # would clobber a resident group's overflow
                        # residue sharing the (key, pane) cell
                        fold_cold({
                            "key_hi": l_hi, "key_lo": l_lo,
                            "pane": l_pane, "value": l_val,
                            "fresh": np.ones(len(l_pane), bool),
                        }, None)
                    # the swap changed these groups' rows without the
                    # kernels marking them: dirty bits keep the next
                    # incremental checkpoint honest
                    for g in dem + pro:
                        kg_dirty_h[s, g] = True
                state = dataclasses.replace(
                    state,
                    kg_dirty=jax.device_put(
                        kg_dirty_h, ctx.state_sharding
                    ),
                )
            tm.apply(plan)
            tier_mask_dev[0] = jnp.asarray(tm.mask())

        def _tier_maintenance():
            """Poll-cycle tier pass (the elastic-latch seam): rank
            groups on the flight recorder's kg-heat/recency series plus
            the watermark-derived next-fire pane, and apply any swap at
            this cycle's cut. Planning is pure host numpy; a cycle with
            an empty plan costs no device traffic at all."""
            tm = tier_mgr[0]
            if tm is None or state is None or win is None:
                return
            dt = drain_telem[0]
            maxp = ctx.max_parallelism
            heat = getattr(dt, "_kg_heat", None) if dt is not None \
                else None
            if heat is not None and len(heat) == maxp:
                heat = np.asarray(heat, np.float64)
                last = np.asarray(dt._kg_last, np.int64)
                seq = int(dt._kg_seq)
            else:
                # no recorder (drain-stats off): heat is flat and the
                # watermark prefetch signal alone drives placement
                heat = np.zeros(maxp, np.float64)
                last = np.full(maxp, -1, np.int64)
                seq = 0
            plan = tm.plan(
                heat, last, seq,
                wm_pane=(
                    host_fired_pane + 1
                    if host_fired_pane > -(2 ** 61) else None
                ),
            )
            _apply_tier_plan(plan)

        columnar_emit = (
            len(pipe.branches) == 1
            and not pipe.branches[0][0]
            and all(s.columnar for s in pipe.all_sinks)
        )
        # on-chip fire reduction (Sink.device_reduce): only aggregate
        # scalars leave the device per drain. Requires the trivially
        # columnar topology and no host-side result projection; the spill
        # tier is checked per-drain (ovf_stores may appear mid-job).
        sink_device_reduce = (
            columnar_emit
            and wagg.result_fn is None
            and all(getattr(s, "device_reduce", False)
                    for s in pipe.all_sinks)
        )

        def _merge_spill(khi, klo, end_ms, v, due_end_ticks,
                         appendable_ends=()):
            """Merge host spill-tier contributions into an emission: keys
            present in both get combined (a key's records can split across
            device and spill when the table filled mid-pane); spill-only
            keys append as new emission rows."""
            k64 = (khi.astype(np.uint64) << np.uint64(32)) | klo.astype(
                np.uint64
            )
            v2 = v.reshape(len(v), ovf_w).astype(np.float32, copy=True)
            add_hi, add_lo, add_end, add_val = [], [], [], []
            for e_ticks in due_end_ticks:
                end_pane = e_ticks // win.slide_ticks - 1
                uk, uv = spill_window_contrib(end_pane)
                if not len(uk):
                    continue
                e_ms = td.to_ms(e_ticks)
                sel = np.nonzero(end_ms == e_ms)[0]
                # batch match: emission keys of this end against the sorted
                # unique spill keys (a key appears at most once per end —
                # shards own disjoint key groups)
                pos = np.searchsorted(uk, k64[sel])
                pos_c = np.minimum(pos, len(uk) - 1)
                hit = uk[pos_c] == k64[sel]
                hit_rows = sel[hit]
                v2[hit_rows] = host_combine(v2[hit_rows], uv[pos_c[hit]])
                if e_ticks in appendable_ends:
                    # spill-only keys fire too (on-time lanes only)
                    only = np.ones(len(uk), bool)
                    only[pos_c[hit]] = False
                    if only.any():
                        ks = uk[only]
                        add_hi.append(
                            (ks >> np.uint64(32)).astype(np.uint32)
                        )
                        add_lo.append(
                            (ks & np.uint64(0xFFFFFFFF)).astype(np.uint32)
                        )
                        add_end.append(np.full(len(ks), e_ms, np.int64))
                        add_val.append(uv[only])
            if add_hi:
                khi = np.concatenate([khi] + add_hi)
                klo = np.concatenate([klo] + add_lo)
                end_ms = np.concatenate([end_ms] + add_end)
                v2 = np.concatenate([v2] + add_val)
            return khi, klo, end_ms, v2.reshape((len(v2),) + v.shape[1:])

        def emit_fires(cf, counts, lanes, ends, vsums, reduced):
            """Emit one fire result. `counts/lanes/ends/vsums` are the
            already-fetched small per-lane fields (ONE batched d2h in
            drain_fires — a cold read costs ~70ms fixed on this runtime,
            so the drain never pays it twice per iteration).

            reduced=True: cf is a wk.ReducedFires — per-lane scalars were
            reduced on-chip, the drain completes from the small fields
            alone and NOTHING O(fires) exists on device, let alone crosses
            the ~25MB/s device->host link. Otherwise cf is a CompactFires
            and only [:count] slices of the device-packed key/value
            buffers transfer. Spill-tier contributions merge in BEFORE any
            result projection."""
            if reduced or (sink_device_reduce and not ovf_stores):
                n = int((counts * lanes).sum())
                if n == 0:
                    return 0
                vs = float((vsums * lanes).sum(dtype=np.float64))
                metrics.fires += n
                metrics.records_out += n
                for s in pipe.all_sinks:
                    s.invoke_reduced(n, vs)
                return n
            slices, end_l = [], []
            # distinct due window ends (ticks). Spill contributions merge
            # into every fired value, but spill-ONLY keys append as new
            # rows solely for ON-TIME lanes (f < F): late lanes are
            # per-key corrections and must not re-emit unrelated keys.
            due_ends = set()
            appendable_ends = set()
            F_on = win.fires_per_step
            for sh in range(counts.shape[0]):
                for f in np.nonzero(lanes[sh])[0]:
                    due_ends.add(int(ends[sh, f]))
                    if f < F_on:
                        appendable_ends.add(int(ends[sh, f]))
                    n = int(counts[sh, f])
                    if n == 0:
                        continue
                    slices.append((cf.key_hi[sh, f, :n], cf.key_lo[sh, f, :n],
                                   cf.values[sh, f, :n]))
                    end_l.append(
                        np.full(n, td.to_ms(int(ends[sh, f])), np.int64)
                    )
            if not slices and not ovf_stores:
                return 0
            # one batched fetch: the lazy device slices transfer together
            # instead of 3 blocking round trips per (shard, lane)
            fetched = jax.device_get(slices)
            khi_l = [s[0] for s in fetched]
            klo_l = [s[1] for s in fetched]
            val_l = [s[2] for s in fetched]
            if slices:
                khi = np.concatenate(khi_l)
                klo = np.concatenate(klo_l)
                end_ms = np.concatenate(end_l)
                v = np.concatenate(val_l)
            else:
                khi = np.zeros(0, np.uint32)
                klo = np.zeros(0, np.uint32)
                end_ms = np.zeros(0, np.int64)
                v = np.zeros((0,) + tuple(np.shape(cf.values)[3:]), np.float32)
            if ovf_stores and due_ends:
                khi, klo, end_ms, v = _merge_spill(
                    khi, klo, end_ms, v, sorted(due_ends), appendable_ends
                )
            if len(v) == 0:
                return 0
            if emit_wagg.result_fn is not None:
                # chained graphs surface the FINAL stage's fires, so the
                # final stage's projection applies (emit_wagg == wagg
                # for single-stage jobs)
                v = np.asarray(emit_wagg.result_fn(v))
            metrics.fires += len(v)
            if columnar_emit:
                kid = (khi.astype(np.uint64) << np.uint64(32)) | klo.astype(
                    np.uint64
                )
                cols = {"key_id": kid, "window_end_ms": end_ms, "value": v}
                metrics.records_out += len(v)
                for s in pipe.all_sinks:
                    s.invoke_columnar(cols)
                return len(v)
            keys = codec.decode(khi, klo)
            out = [
                WindowResult(k, int(e), vv)
                for k, e, vv in zip(keys, end_ms.tolist(), v.tolist())
            ]
            return _emit_batch(pipe, out, metrics)

        class _SubstepFires:
            """Per-sub-step view of a fired megastep's stacked
            CompactFires ([n_shards, K, ...] leaves): lazy [:, k] payload
            slices that materialize only through emit_fires' [:count]
            fetches — a no-fire sub-step transfers nothing."""

            __slots__ = ("key_hi", "key_lo", "values")

            def __init__(self, cf, kk):
                self.key_hi = cf.key_hi[:, kk]
                self.key_lo = cf.key_lo[:, kk]
                self.values = cf.values[:, kk]

        def consume_fires(force: bool = False):
            """Drain lagged resident-pipeline fire payloads, oldest
            first (emission order == fire order). In steady state a
            handle sits FIRE_LAG dispatches before being read, so the
            device long since materialized it and the fetch is one
            settled round trip — the resident pipeline's analog of the
            lagged monitoring channel. ``force`` empties the queue at
            ordering boundaries: any split drain, checkpoint/savepoint
            cuts (emissions must precede the snapshot so a crash cannot
            strand a fire the restored fired_through already counts),
            idle polls and end of stream (latency guard)."""
            total = 0
            while fire_watch and (force or len(fire_watch) > FIRE_LAG):
                cf, ovf_h, t_disp, ds_h = fire_watch.popleft()
                # ReducedFires payloads (device_reduce topologies) have
                # no key planes: the small fields below ARE the drain
                reduced = not hasattr(cf, "key_hi")
                t_f0 = time.perf_counter()
                if ds_h is not None:
                    # the sampled flight-recorder payload rides the SAME
                    # batched lagged fetch — one settled round trip
                    # either way, never a fresh sync
                    counts, lanes, ends, vsums, ovf_fill, ds_np = \
                        jax.device_get(
                            (cf.counts, cf.lane_valid,
                             cf.window_end_ticks, cf.value_sums,
                             ovf_h, ds_h)
                        )
                else:
                    ds_np = None
                    counts, lanes, ends, vsums, ovf_fill = jax.device_get(
                        (cf.counts, cf.lane_valid, cf.window_end_ticks,
                         cf.value_sums, ovf_h)
                    )                          # [n_shards, K, Ft]
                if win.overflow and int(ovf_fill.max(initial=0)) > 0:
                    # spill contributions for the fired panes may still
                    # sit in the device overflow ring — move them into
                    # the host pane stores BEFORE the emission merge
                    # (the split drain orders drain_overflow the same
                    # way; entries landing after a window fired are
                    # late-dropped on device, so over-draining is safe)
                    drain_overflow()
                t_f1 = time.perf_counter()
                fires_before = metrics.fires
                n = 0
                for kk in range(counts.shape[1]):
                    if not lanes[:, kk].any():
                        continue
                    n += emit_fires(
                        None if reduced else _SubstepFires(cf, kk),
                        counts[:, kk], lanes[:, kk], ends[:, kk],
                        vsums[:, kk], reduced,
                    )
                dt = drain_telem[0]
                if dt is not None:
                    if ds_np is not None:
                        if isinstance(ds_np, tuple):
                            # chained-drain payload pair (ISSUE 17):
                            # stage-0 per-slot stack + per-stage records
                            dt.absorb_payload(ds_np[0])
                            dt.absorb_stage_payload(ds_np[1])
                        else:
                            dt.absorb_payload(ds_np)
                    live = lanes.astype(bool)
                    if live.any():
                        # event-time-to-fire: every live lane is one
                        # fired window end weighted by its key count
                        dt.note_fires(list(zip(
                            ends[live].tolist(), counts[live].tolist()
                        )))
                if tracer is not None and tracer.active:
                    tracer.rec("fire", t_f0, t_f1, fused=True)
                    tracer.rec("emit", t_f1, fired=n)
                if n:
                    metrics.record_fire_latency(
                        metrics.fires - fires_before,
                        (time.perf_counter() - t_disp) * 1e3,
                    )
                    rec_tracker.note_fire()
                    if self._latency_hist is not None and \
                            last_ingest_t[0] is not None:
                        self._latency_hist.update(
                            (time.perf_counter() - last_ingest_t[0]) * 1e3
                        )
                total += n
                phase_acc["emit"] += time.perf_counter() - t_f0
            return total

        def drain_chained(wm_ms, t_cross=None):
            """Chained-graph analog of drain_fires. There is NO
            standalone fire step for a stage chain (a bare fire sweep
            would consume stage-0 fires without feeding stage 1), so
            residual due panes are flushed by dispatching EMPTY chained
            drain rounds at the target watermark: each round fires up
            to F window ends per stage and forwards them one edge down
            inside the scan. ceil((ring + panes_per_window) / F) rounds
            per stage plus one hop per edge bound the flush; steady-
            state polls never reach the loop (in-scan fires ride the
            lagged consume path, same as the single-stage resident
            drain)."""
            t_e0 = time.perf_counter()
            # pending resident-pipeline payloads predate this flush
            total = consume_fires(force=True)
            if td is None or wm_ms is None:
                phase_acc["emit"] += time.perf_counter() - t_e0
                return total
            fires_before = metrics.fires
            route = (
                "sharded" if "sharded" in residents_by_route else "mask"
            )
            rounds = len(chain_specs) + 1
            for sp in (spec,) + tuple(chain_specs):
                w = sp.win
                rounds += -(
                    -(w.ring + w.size_ticks // w.slide_ticks)
                    // w.fires_per_step
                )
            for _ in range(rounds):
                args, _, _ = _empty_fused_item(route)
                run_update_resident(route, [(args, wm_ms, None)])
            total += consume_fires(force=True)
            if t_cross is not None:
                metrics.record_fire_latency(
                    metrics.fires - fires_before,
                    (time.perf_counter() - t_cross) * 1e3,
                )
            phase_acc["emit"] += time.perf_counter() - t_e0
            return total

        def drain_fires(wm_ms, t_cross=None):
            """Fire every due window end at watermark wm_ms. One fire step
            evaluates up to F window ends (+ up to F late re-fires); loop
            while a full lane set came back, meaning backlog may remain.

            t_cross: perf_counter() at the moment the host observed the
            watermark crossing; every window emitted by this drain records
            (now - t_cross) as its fire latency (the p99 half of the
            north-star metric; ref WindowOperator.onEventTime drain)."""
            if graph is not None:
                return drain_chained(wm_ms, t_cross)
            dbg = os.environ.get("FLINK_TPU_DRAIN_DEBUG")
            t_e0 = time.perf_counter()
            # pending resident-pipeline payloads predate this drain's
            # fires (and prune_stores below must not outrun them)
            consume_fires(force=True)
            drain_overflow()     # ring -> pane stores before any emission
            # skew telemetry: refresh the per-key-group occupancy view ON
            # ENTRY (interval-limited inside) — the fires below purge due
            # panes, so sampling here sees the live population the stall
            # is actually about
            refresh_kg_occupancy()
            t_ovf = time.perf_counter()
            if dbg:
                print(f"[drain] ovf={1e3*(t_ovf-t_e0):.0f}ms",
                      file=sys.stderr)
            total = 0
            F = win.fires_per_step
            # spill-tier presence is fixed for the whole drain
            # (drain_overflow above was its only producer), so the choice
            # of fire variant is loop-invariant
            use_reduced = fire_reduced_step is not None and not ovf_stores
            traced = tracer is not None and tracer.active
            while True:
                t_f0 = time.perf_counter()
                # watchdog phases: fire dispatch and the barrier fetch
                # are the step loop's device waits — a wedged ensemble
                # hangs HERE, so these arms buy the attribution
                wd_prev = wd.arm("fire") if wd is not None else None
                try:
                    cf = run_fire(wm_ms, reduced=use_reduced)
                    # fire dispatch returns immediately; the device_get
                    # below IS the step-boundary barrier — trace them
                    # separately so a stalled fetch is attributable
                    t_fd = time.perf_counter() if traced else None
                    if wd is not None:
                        wd.arm("barrier_fetch")
                    # ONE batched fetch of all small per-lane fields
                    counts, lanes, ends, vsums = jax.device_get(
                        (cf.counts, cf.lane_valid, cf.window_end_ticks,
                         cf.value_sums)
                    )
                finally:
                    if wd is not None:
                        wd.disarm(wd_prev)
                t_f1 = time.perf_counter()
                fires_before = metrics.fires
                n_emit = emit_fires(cf, counts, lanes, ends, vsums,
                                    use_reduced)
                if traced:
                    t_em = time.perf_counter()
                    tracer.rec("fire", t_f0, t_fd, reduced=use_reduced)
                    tracer.rec("barrier_fetch", t_fd, t_f1)
                    tracer.rec("emit", t_f1, t_em, fired=n_emit)
                if dbg:
                    print(f"[drain] fire+lanes={1e3*(t_f1-t_f0):.0f}ms "
                          f"emit={1e3*(time.perf_counter()-t_f1):.0f}ms "
                          f"n={n_emit}", file=sys.stderr)
                total += n_emit
                if t_cross is not None:
                    # weight by WINDOWS fired (metrics.fires delta), not by
                    # post-chain records out — a filter/flatMap after the
                    # window must not skew the per-window percentile
                    metrics.record_fire_latency(
                        metrics.fires - fires_before,
                        (time.perf_counter() - t_cross) * 1e3,
                    )
                on_time = int(lanes[:, :F].sum(axis=1).max(initial=0))
                late = int(lanes[:, F:].sum(axis=1).max(initial=0))
                if on_time < F and late < F:
                    prune_stores(wm_ms)
                    phase_acc["emit"] += time.perf_counter() - t_e0
                    if total:
                        # the first emission after a restore stamps the
                        # detect-to-first-fire MTTR number (no-op in
                        # steady state)
                        rec_tracker.note_fire()
                    if total and self._latency_hist is not None and \
                            last_ingest_t[0] is not None:
                        # LatencyMarker analog: ingest -> sink for the
                        # youngest records feeding this emission
                        self._latency_hist.update(
                            (time.perf_counter() - last_ingest_t[0]) * 1e3
                        )
                    return total

        def batch_loop():
            end = False
            while not end:
                end = poll_cycle()

        # Host-side fire scheduling: a window only becomes due when the
        # watermark crosses a pane boundary. The host computes the
        # watermark, so between crossings it dispatches update-only steps
        # with no device readback at all. With allowedLateness > 0, late
        # records can make already-fired windows due again at ANY step, so
        # fires are drained eagerly every cycle (matching round-1 timing).
        host_fired_pane = -(2**62)
        # newest pane the ring has absorbed; guards the BETWEEN-polls time
        # jump (see the pre-fire in poll_cycle — the catch-up slicing only
        # covers a jump WITHIN one poll)
        applied_max_pane = None
        eager_fire = wagg.allowed_lateness_ms > 0

        def wm_pane_of(wm_ms) -> int:
            wm_ticks = min(int(td.to_ticks(wm_ms)), 2**31 - 4)
            b = max(wm_ticks, -(2**31) + 1 + slide_ms)
            return (b + 1 - slide_ms) // slide_ms   # floor div, as on device

        def prep_batch():
            """Front half of a cycle: source poll + host chain + key/value/
            timestamp encode. Pure host numpy with no dependence on mutable
            executor state (watermarks, time domain, device handles), so
            the prefetch thread can run it strictly ahead of the apply
            half — the encode of batch k+1 overlaps the device step of
            batch k instead of serializing with it. The post-poll offsets
            ride the batch (the epoch-tagged replay point): checkpoints
            snapshot the offsets of the last APPLIED batch, which is what
            makes running ahead compatible with exactly-once cuts."""
            polled, end, offsets = pipe.source.poll_with_offsets(B)
            t_src = time.perf_counter()
            now_ms = int(time.time() * 1000)
            hi = lo = values = None
            ts_ms = None
            n = 0
            if pipe.source.columnar and isinstance(polled, tuple):
                cols, ts_ms = polled
                if cols:
                    # columnar chain ops transform the column dict itself
                    for t in pipe.pre_chain:
                        if t.kind != "map":
                            raise NotImplementedError(
                                f"columnar sources support only 'map' "
                                f"(dict->dict) before key_by, got {t.kind!r}"
                            )
                        cols = t.fn(cols)
                    # selectors index the column dict (key_by('name') etc.)
                    keys_arr = np.asarray(pipe.key_by.key_selector(cols))
                    n = len(keys_arr)
                    hi, lo = codec.encode(keys_arr, keep_reverse=keep_rev)
                    values = wagg.extractor(cols)
                    values = (
                        wagg.value_prep(values) if wagg.value_prep is not None
                        else np.asarray(values)
                    )
                    if event_time:
                        if pipe.ts_transform is not None:
                            ts_ms = np.asarray(
                                pipe.ts_transform.timestamp_fn(cols), np.int64
                            )
                        elif ts_ms is None:
                            raise ValueError(
                                "event-time job but the columnar source "
                                "provides no timestamps and no "
                                "assign_timestamps_and_watermarks is set"
                            )
                    else:
                        ts_ms = np.full(n, now_ms, np.int64)
            else:
                elements = _apply_chain(pipe.pre_chain, self._to_elements(polled))
                n = len(elements)
                if n:
                    keys = [pipe.key_by.key_selector(e) for e in elements]
                    hi, lo = codec.encode(keys, keep_reverse=keep_rev)
                    raw = [wagg.extractor(e) for e in elements]
                    values = (
                        wagg.value_prep(raw) if wagg.value_prep is not None
                        else np.asarray(raw, np.float32)
                    )
                    if event_time and pipe.ts_transform is not None:
                        ts_ms = np.asarray(
                            [pipe.ts_transform.timestamp_fn(e) for e in elements],
                            np.int64,
                        )
                    else:
                        ts_ms = np.full(n, now_ms, np.int64)
            return ingest_mod.PreppedBatch(
                end=end, n=n, now_ms=now_ms, t_src=t_src, offsets=offsets,
                hi=hi, lo=lo, values=values, ts_ms=ts_ms,
            )

        # -- pipelined ingest (runtime/ingest.py): epoch-tagged prefetch,
        # async device staging, off-thread route planning. Checkpoint-
        # COMPATIBLE: every prepped batch carries its post-poll offsets,
        # snapshots cut at the applied offsets, and a restore's epoch
        # bump discards in-flight batches (they replay from the rewound
        # source) — so the overlap runs in the production configuration
        # too, where it used to be hard-disabled. The reference overlaps
        # the same way structurally (netty IO threads fill input buffers
        # while the task thread processes, SURVEY §2.3); one thread is
        # enough because the prep half is vectorized numpy. "off" remains
        # the fully-serial escape hatch.
        prefetch_cfg = env.config.get_str("pipeline.prefetch", "auto")
        if prefetch_cfg not in ("auto", "on", "off"):
            raise ValueError(
                f"pipeline.prefetch must be auto|on|off, got {prefetch_cfg!r}"
            )
        use_prefetch = prefetch_cfg != "off"
        # the applied-offset cut only works when restore can REWIND the
        # source to it: a non-replayable source (snapshot_offsets None —
        # sockets, transient rings) cannot replay the batches a restore's
        # epoch bump discards, so running ahead of a possible snapshot
        # (checkpointing on, or a control channel that can request a
        # savepoint) would turn at-most-once into silently-more-lost.
        # auto falls back to inline prep there; an explicit "on" is a
        # config error, not a silent downgrade.
        can_snapshot = (
            storage is not None
            or getattr(env, "_control", None) is not None
        )
        if can_snapshot and pipe.source.snapshot_offsets() is None:
            if prefetch_cfg == "on":
                raise ValueError(
                    "pipeline.prefetch=on with checkpointing/savepoints "
                    "requires a replayable source (snapshot_offsets "
                    "returning a position): this source cannot rewind to "
                    "the applied-offset cut, so batches prefetched past a "
                    "snapshot would be lost on restore"
                )
            use_prefetch = False
        staging_cfg = env.config.get_str("pipeline.device-staging", "auto")
        if staging_cfg not in ("auto", "on", "off"):
            raise ValueError(
                f"pipeline.device-staging must be auto|on|off, "
                f"got {staging_cfg!r}"
            )
        if staging_cfg == "on" and not use_prefetch:
            raise ValueError(
                "pipeline.device-staging=on requires pipeline.prefetch: "
                "the staging transfer-completion wait runs on the ingest "
                "thread and would otherwise block the step loop"
            )
        use_staging = use_prefetch and staging_cfg != "off"
        # -- finalize the resident loop (validated where res_cfg was
        # read): the drain consumes ring-published STAGED batches, so
        # "on" without the prefetch+staging substrate is a config error,
        # and "auto" lights up exactly when the fused-fire resident
        # pipeline is active with staging available
        if res_cfg in ("on", "while"):
            if not use_staging:
                raise ValueError(
                    f"pipeline.resident-loop={res_cfg} requires pipeline."
                    "prefetch + pipeline.device-staging: the drain "
                    "consumes device-staged batches published into the "
                    "HBM ring by the ingest thread"
                )
            use_resident = True
            # while-drain platform gate: CPU buffer donation does not
            # alias, so the in-kernel cursor re-read can never observe a
            # mid-drain publish there — keep the scan drain unless the
            # declared test/bench escape hatch is on (where the while
            # kernel degrades, bit-exactly, to the scan's count gating)
            use_while = res_cfg == "while" and (
                jax.default_backend() != "cpu" or wd_cpu_override
            )
        else:
            # auto is PLATFORM-gated like precombine/packed-planes: the
            # drain retires a ~100ms tunneled host round trip per
            # megastep on accelerators, but on CPU dispatch costs
            # microseconds and the extra drain-kernel compiles would be
            # pure warmup overhead
            use_resident = (
                res_cfg == "auto" and use_fused_fire and use_staging
                and jax.default_backend() != "cpu"
            )
            if graph is not None and res_cfg == "auto":
                # a chained stage graph CANNOT run outside the resident
                # drain (stage edges live inside the drain scan), so
                # auto lights it up whenever the staging substrate
                # exists — on every backend, with or without dispatch
                # fusion; setup()'s check_runtime is the loud backstop
                # when staging is off or resident-loop was forced off
                use_resident = use_staging
        if use_resident:
            # the drain group IS the ring: accumulator capacity tracks
            # ring depth, and groups always hold fires (the drain fires
            # in-scan per slot). While mode accumulates up to the
            # while-drain bound instead — batches published while the
            # previous drain was in flight join the CURRENT dispatch
            # (beyond ring depth they ride unringed fresh staging), so
            # a publish landing mid-drain never forces its own dispatch
            fused = ingest_mod.FusedBatchAccumulator(
                wd_max_slots if use_while else ring_depth,
                hold_fires=True,
            )
        # -- finalize data parallelism (validated where dp_cfg was
        # read): the sharded drain is a shard_map'd variant of the
        # resident drain, so it needs the ring substrate AND a mesh
        # with more than one shard to be worth the extra compiles
        if dp_cfg == "on":
            if not use_resident:
                raise ValueError(
                    "pipeline.data-parallel=on requires the resident "
                    "loop (pipeline.resident-loop + prefetch + device "
                    "staging): the sharded drain consumes per-shard "
                    "ring slices published by the ingest thread"
                )
            use_dp = True
        else:
            use_dp = (
                dp_cfg == "auto" and use_resident and ctx.n_shards > 1
            )
        ingest = ingest_mod.IngestPipeline(
            prep_batch, prefetch=use_prefetch,
            initial_offsets=pipe.source.snapshot_offsets(),
            depth=env.config.get_int("pipeline.prefetch-depth", 2),
            ring_depth=env.config.get_int("pipeline.staging-ring-depth", 2),
            tracer=tracer,
        )
        # checkpoint-complete offset commits may ride the poll's wire
        # connection: serialize them with the producer's polls
        ck_io.source_lock = ingest.source_lock

        # -- self-tuning runtime controller (runtime/controller.py;
        # ISSUE 19, ROADMAP item 3): the closed loop over the doctor's
        # findings + the raw regime/heat planes, serviced at the poll-
        # cycle boundary below. Constructed ONLY when controller.enabled
        # is on — the shipping default (off) builds nothing here, reads
        # no sensor, registers no gauge: the off path stays byte-neutral
        # (no new dispatches, drain kernels untouched).
        runtime_ctl = [None]

        def _controller_sensor():
            """One host dict of the planes the controller decides on —
            all already-fetched telemetry (regime/heat EWMAs maintained
            by the lagged consume path), never a fresh device sync."""
            dt = drain_telem[0]
            duty = starved = None
            heat = None
            if dt is not None:
                duty, starved = dt.regime()
                h = getattr(dt, "_kg_heat", None)
                if h is not None and len(h) == ctx.max_parallelism:
                    heat = np.array(h, np.float64)
            starts_c, ends_c = ctx.kg_bounds()
            return {
                "records": int(metrics.records_in),
                "duty": duty, "starved": starved, "heat": heat,
                "kg_starts": [int(x) for x in starts_c],
                "kg_ends": [int(x) for x in ends_c],
            }

        def _controller_rebalance(starts, ends):
            """Apply a heat-balanced re-slice LIVE through the same
            savepoint-cut machinery as the elastic scale-up — exactly-
            once preserved (tiers re-slice inside setup(), the
            incremental chain re-bases). On ANY failure the pre-
            rebalance slicing re-latches so recovery re-plans the mesh
            the job actually ran on, not the half-applied target."""
            if td is None or state is None:
                raise RuntimeError(
                    "controller rebalance before the job has state")
            # chaos seam: a crash here lands mid-rebalance, BEFORE the
            # cut — restart must recover exactly-once from the last
            # completed checkpoint (tests/test_controller.py)
            faults.inject(
                "controller.apply",
                ends=[int(e) for e in ends],
                n_shards=ctx.n_shards,
            )
            prev = kg_slices_hold[0]
            kg_slices_hold[0] = tuple(
                (int(s), int(e)) for s, e in zip(starts, ends)
            )
            try:
                _rescale_live(
                    list(np.asarray(ctx.mesh.devices).flat),
                    "rebalance", "controller heat rebalance",
                )
            except BaseException:
                kg_slices_hold[0] = prev
                raise

        if env.config.get(_CoreOpts.CONTROLLER_ENABLED):
            _acts = {}
            if use_resident:
                # effective drain fill target: the accumulator's
                # capacity is a plain attribute the count-gated drain
                # serves at ANY fill level 1..ring_depth — a live write,
                # zero recompiles. Down = drain earlier (ring-starved
                # regime), up = amortize dispatch cost (saturated).
                def _rf_set(v):
                    fused.k = int(v)

                _acts["ring-fill-target"] = controller_mod.Actuator(
                    "ring-fill-target", lambda: int(fused.k), _rf_set,
                    lo=1, hi=ring_depth,
                )
            elif k_fuse > 1:
                # without the resident ring the same attribute is the
                # megastep grouping (pipeline.steps-per-dispatch):
                # shrinking it bounds recompile exposure per dispatch
                def _dg_set(v):
                    fused.k = int(v)

                _acts["dispatch-group"] = controller_mod.Actuator(
                    "dispatch-group", lambda: int(fused.k), _dg_set,
                    lo=1, hi=k_fuse,
                )
            if drain_stats_on:
                def _ds_set(v):
                    drain_stats_every[0] = max(1, int(v))

                _acts["drain-stats-cadence"] = controller_mod.Actuator(
                    "drain-stats-cadence",
                    lambda: int(drain_stats_every[0]), _ds_set,
                    lo=1, hi=64,
                )
            if tier_budget_cfg > 0:
                def _tp_get():
                    tm = tier_mgr[0]
                    if tm is not None:
                        return int(tm.prefetch_ahead_panes)
                    return int(env.config.get(
                        _CoreOpts.STATE_TIERS_PREFETCH_AHEAD_PANES))

                def _tp_set(v):
                    tm = tier_mgr[0]
                    if tm is not None:
                        tm.prefetch_ahead_panes = max(0, int(v))

                _acts["tier-prefetch-ahead"] = controller_mod.Actuator(
                    "tier-prefetch-ahead", _tp_get, _tp_set,
                    lo=0, hi=16, step="additive",
                )

            runtime_ctl[0] = controller_mod.RuntimeController(
                _acts, _controller_sensor,
                findings_fn=lambda: (
                    (doctor_report() or {}).get("findings") or []
                ),
                rebalancer=_controller_rebalance,
                interval_cycles=int(env.config.get(
                    _CoreOpts.CONTROLLER_INTERVAL_CYCLES)),
                revert_threshold=float(env.config.get(
                    _CoreOpts.CONTROLLER_REVERT_THRESHOLD)),
                probation_cycles=int(env.config.get(
                    _CoreOpts.CONTROLLER_PROBATION_CYCLES)),
                cooldown_cycles=int(env.config.get(
                    _CoreOpts.CONTROLLER_COOLDOWN_CYCLES)),
                rebalance_threshold=float(env.config.get(
                    _CoreOpts.CONTROLLER_REBALANCE_THRESHOLD)),
                min_rebalance_interval=float(env.config.get(
                    _CoreOpts.CONTROLLER_MIN_REBALANCE_INTERVAL)),
                min_gain=float(env.config.get(
                    _CoreOpts.CONTROLLER_MIN_GAIN)),
                # durable decisions (ISSUE 20 satellite): the ledger
                # rides the checkpoint dir so a restarted job serves
                # the merged tuning history at /jobs/<jid>/controller
                persist_dir=env.checkpoint_dir or None,
            )
            if self._job_group is not None:
                grp_c = self._job_group

                def _ctl_ctr(field):
                    ctl = runtime_ctl[0]
                    return int(getattr(ctl, field)) if ctl else 0

                grp_c.gauge("controller_actions",
                            partial(_ctl_ctr, "actions"))
                grp_c.gauge("controller_reverts",
                            partial(_ctl_ctr, "reverts"))
                grp_c.gauge("controller_rebalances",
                            partial(_ctl_ctr, "rebalances"))

        def controller_report() -> dict:
            """/jobs/<jid>/controller body: the decision ledger +
            actuator/counter view (or the off stub)."""
            ctl = runtime_ctl[0]
            if ctl is None:
                return {
                    "available": False,
                    "reason": "controller.enabled off",
                }
            return ctl.report()

        env._controller_report = controller_report

        def _apply_planned(pb):
            """Apply one PLANNED single-group batch: the ingest side
            already chose the route and (with staging on) moved the
            padded arrays to the device, so this path is watermark
            arithmetic + one dispatch — no hashing, no padding, no
            per-batch allocation on the step-loop thread.

            With dispatch fusion on (pipeline.steps-per-dispatch=K > 1)
            the batch lands in the fused slot instead; the slot flushes
            as ONE megastep when full, and EARLY on a route/staging
            change or a fire boundary (fires must see every pending
            update, and a group never spans a pane crossing — fire
            timing matches the sequential path). Returns True when the
            batch is still pending in the slot: the caller must NOT mark
            its offsets applied — the flush does, at the megastep
            boundary (the exactly-once cut)."""
            nonlocal applied_max_pane, host_fired_pane
            wm_ms = (
                wm_strategy.on_batch(pb.ts_max) if event_time
                else pb.now_ms - 1
            )
            slide = int(win.slide_ticks)
            # BETWEEN-polls time jump guard (see _apply_general): the
            # planned batch is single-group by construction, but may
            # still sit past everything the ring has absorbed
            g_max_pane = pb.ticks_max // slide
            if (
                applied_max_pane is not None
                and g_max_pane - applied_max_pane >= 2
            ):
                g_min_pane = pb.ticks_min // slide
                fire_wm = min(wm_ms, int(td.to_ms(g_min_pane * slide)) - 1)
                flush_fused()   # pending updates may feed the panes fired
                drain_fires(fire_wm, time.perf_counter())
            applied_max_pane = (
                g_max_pane if applied_max_pane is None
                else max(applied_max_pane, g_max_pane)
            )
            wp = wm_pane_of(wm_ms)
            fire_now = eager_fire or wp > host_fired_pane
            deferred = False
            # resident pipeline: a crossing no longer breaks the group —
            # the fused-fire megastep fires it INSIDE the scan, and
            # flush_fused owns the crossing bookkeeping for this batch
            in_slot = (
                (k_fuse > 1 and pb.route in megasteps_by_route)
                # resident loop: the drain group accumulates regardless
                # of steps-per-dispatch — the count-gated drain
                # dispatches ANY fill level as one scan
                or (use_resident and pb.route in residents_by_route)
            )
            in_scan = fused.hold_fires and in_slot
            if in_slot:
                if pb.staged is not None:
                    args, staged_mode = pb.staged, True
                else:
                    args, staged_mode = _stage_planned(
                        _pad_planned(pb), pb.route
                    )
                if not fused.compatible(pb.route, staged_mode):
                    flush_fused()
                fused.push(args, wm_ms, pb, pb.route, staged_mode)
                if fused.full() or (fire_now and not in_scan):
                    flush_fused()
                else:
                    deferred = True
            elif pb.staged is not None:
                run_update(None, None, None, None, None, wm_ms,
                           staged=pb.staged, route=pb.route)
            else:
                run_update(*_pad_planned(pb), wm_ms, route=pb.route)
            if fire_now and not in_scan:
                drain_fires(wm_ms, time.perf_counter())
                host_fired_pane = wp
            return deferred

        def poll_cycle():
            nonlocal td, host_fired_pane, applied_max_pane
            self._poll_control()
            # scale-back-up (runtime/elastic.py): a latched operator
            # request is serviced at the cycle boundary — a savepoint-
            # cut live rescale back to full capacity. The latch is
            # consumed only when the rescale can actually run (job has
            # state AND is degraded): a request filed early — or before
            # a loss even lands — stays pending until it applies.
            if td is not None and elastic_ctl.degraded and \
                    elastic_ctl.take_scale_up_request():
                try:
                    _rescale_live(
                        list(elastic_ctl.full_devices), "scale_up",
                        "operator scale-up request",
                    )
                except BaseException:
                    # the latch was consumed but the rescale never
                    # completed: re-latch so the request survives the
                    # recovery restart instead of being silently lost
                    # (ISSUE 19 bugfix)
                    elastic_ctl.request_scale_up()
                    raise
            # tiered state maintenance rides the same cycle-boundary
            # seam: residency swaps happen between dispatches, at a cut
            if tier_mgr[0] is not None and td is not None:
                _tier_maintenance()
            # self-tuning controller (ISSUE 19): same seam — at most one
            # knob move or rebalance per interval, between dispatches,
            # at a cut. None (the default) costs one list-index check.
            if runtime_ctl[0] is not None and td is not None \
                    and state is not None:
                runtime_ctl[0].service()
            if tracer is not None:
                tracer.begin_cycle()   # sampling decision for this cycle
            t_c0 = time.perf_counter()
            phase_acc["dispatch"] = phase_acc["emit"] = 0.0
            if pending_batch[0] is not None:
                # leftover from the resident greedy ring fill: a batch
                # the drain group could not absorb (idle, end, or
                # unplanned) — it gets this cycle's FULL handling, in
                # the order it was polled
                pb, pending_batch[0] = pending_batch[0], None
            elif wd is None:
                pb = ingest.next()
            else:
                # watchdog "source" phase (off by default): the wait for
                # the prep side — covers a dead prefetch thread or a
                # must-produce source going silent
                wd_prev = wd.arm("source")
                try:
                    pb = ingest.next()
                finally:
                    wd.disarm(wd_prev)
            # attribution: with prefetch on, "source" time is only the
            # wait for the prep thread (~0 while it keeps ahead)
            t_src = time.perf_counter()
            if tracer is not None and tracer.active:
                # source drain + host chain/encode (prefetch folds the
                # encode into the wait; both are upstream of the device)
                tracer.rec("source", t_c0, t_src, records=pb.n)
            end, n, now_ms = pb.end, pb.n, pb.now_ms

            metrics.records_in += n
            deferred = False
            if n:
                last_ingest_t[0] = pb.t_src
                if td is None:
                    # auto-layout hint: bounded non-negative int keys (the
                    # identity fits hi==0, lo < capacity on the first
                    # batch) are eligible for the direct-index backend —
                    # key == slot, no probes, no inserts. setup() combines
                    # this with spillability (out-of-bound keys must have
                    # a spill tier to degrade to, not be dropped). The
                    # first batch is always unplanned (the plan is born in
                    # setup), so its host arrays are present.
                    auto_direct_hint[0] = (
                        int(pb.hi.max(initial=0)) == 0
                        and int(pb.lo.max(initial=0))
                        < env.state_capacity_per_shard
                    )
                    setup((int(np.min(pb.ts_ms)) // size_ms) * size_ms)
                if pb.route is not None:
                    deferred = _apply_planned(pb)
                    # resident loop: greedily absorb every batch the
                    # prefetch queue ALREADY holds into the drain group,
                    # so one cycle consumes ring slots up to the write
                    # cursor instead of one batch per cycle. Each pull
                    # rides _apply_planned (time-jump guard, route
                    # compatibility, flush-on-full all apply); the loop
                    # stops at ring empty (try_next None), a flushed
                    # group (the cycle dispatched its drain), or a
                    # batch the group cannot hold (handled next cycle
                    # via pending_batch, order preserved).
                    while use_resident and deferred:
                        nxt = ingest.try_next()
                        if nxt is None:
                            break
                        if nxt.n and nxt.route is not None \
                                and not nxt.end:
                            metrics.records_in += nxt.n
                            last_ingest_t[0] = nxt.t_src
                            if not _apply_planned(nxt):
                                ingest.mark_applied(nxt)
                                break
                        else:
                            pending_batch[0] = nxt
                            break
                else:
                    _apply_general(pb)
            elif td is not None:
                # idle poll: the source went quiet — apply any pending
                # fused group now (latency guard, and this empty poll's
                # offsets sit PAST the pending batches' polls, so marking
                # them applied below is only correct once they dispatch),
                # and surface any lagged resident-pipeline fires
                flush_fused()
                consume_fires(force=True)
                # idle poll: advance processing-time watermark
                if not event_time:
                    wp = wm_pane_of(now_ms - 1)
                    if wp > host_fired_pane:
                        drain_fires(now_ms - 1, time.perf_counter())
                        host_fired_pane = wp
            if end:
                flush_fused()   # the stream is over: nothing may pend
                consume_fires(force=True)
                deferred = False
            # this batch is now part of the device state: its offsets
            # name the cut the next checkpoint/savepoint snapshots. A
            # batch deferred into the fused slot is NOT part of it yet —
            # its flush marks the cut instead (megastep boundary).
            if not deferred:
                ingest.mark_applied(pb)
            if not kv_mailbox.empty():
                drain_kv_mailbox()
            ck_io.drain()
            if (
                storage is not None
                and env.checkpoint_interval_steps > 0
                and metrics.steps - steps_at_ckpt >= env.checkpoint_interval_steps
                and td is not None
            ):
                # checkpoint.min-pause gate: a due trigger defers until
                # the pause since the last attempt elapses; ONE decline
                # is counted per deferred trigger, not per polled cycle
                if ck_policy.can_trigger():
                    ck_declined[0] = False
                    # write_checkpoint owns the megastep-boundary cut:
                    # its first act flushes any pending fused group
                    write_checkpoint()
                elif not ck_declined[0]:
                    ck_declined[0] = True
                    metrics.checkpoints_declined += 1
            if self._attribution is not None:
                t_end = time.perf_counter()
                src_s = t_src - t_c0
                disp_s = phase_acc["dispatch"]
                emit_s = phase_acc["emit"]
                host_s = max(0.0, (t_end - t_c0) - src_s - disp_s - emit_s)
                self._attribution.record(
                    idle=(n == 0), source=src_s * 1e3, host=host_s * 1e3,
                    dispatch=disp_s * 1e3, emit=emit_s * 1e3,
                )
            return end

        def _apply_general(pb):
            """The general apply path: unplanned batches (before setup, or
            re-planned after restore), catch-up replay spans that must be
            time-sliced, and host-chain polls expanded beyond B lanes."""
            nonlocal host_fired_pane, applied_max_pane
            # dispatch order must match poll order: anything the fused
            # slot still holds precedes this batch
            flush_fused()
            hi, lo, values, ts_ms = pb.hi, pb.lo, pb.values, pb.ts_ms
            n, now_ms = pb.n, pb.now_ms
            ticks = td.to_ticks(ts_ms)
            if event_time:
                wm_ms = wm_strategy.on_batch(int(np.max(ts_ms)))
            else:
                wm_ms = now_ms - 1
            values = np.asarray(values)
            # A batch spanning more panes than the ring holds (replay /
            # catch-up) must be time-sliced, or fresh panes would evict
            # unfired ones. The span bound leaves size/slide panes of
            # headroom (not just 2): every pane the rotation can evict
            # must have ALL of its windows end below the group's min
            # pane, so the safe pre-fire between groups (below) can
            # close them without touching windows the group feeds.
            panes = ticks // np.int32(win.slide_ticks)
            span_limit = win.ring - max(
                2, int(win.size_ticks // win.slide_ticks) + 1
            )
            if span_limit < 1:
                # setup() validates configured rings; this guard keeps a
                # degenerate span from ever entering the grouping loop
                # below, whose cutoff would never advance (an infinite
                # empty-group hang instead of an error)
                raise RuntimeError(
                    f"window ring {win.ring} leaves catch-up span "
                    f"{span_limit} < 1 for a "
                    f"{int(win.size_ticks // win.slide_ticks)}-pane "
                    f"window; raise window.ring-panes"
                )
            if int(panes.max()) - int(panes.min()) >= span_limit:
                order = np.argsort(panes, kind="stable")
                sorted_panes = panes[order]
                groups = []
                lo_i = 0
                while lo_i < n:
                    cutoff = sorted_panes[lo_i] + span_limit
                    hi_i = int(np.searchsorted(sorted_panes, cutoff, "left"))
                    groups.append(order[lo_i:hi_i])
                    lo_i = hi_i
            else:
                groups = None   # single group, no reindex copy
            catch_up = groups is not None
            wp = wm_pane_of(wm_ms)
            ooo_ms = wm_strategy.out_of_orderness_ms
            for sel in (groups if catch_up else (None,)):
                if sel is None:
                    g_hi, g_lo, g_ticks, g_vals, m = hi, lo, ticks, values, n
                    g_wm = wm_ms
                else:
                    g_hi, g_lo, g_ticks, g_vals, m = (
                        hi[sel], lo[sel], ticks[sel], values[sel], len(sel)
                    )
                    # group-local watermark: a replay burst's watermark
                    # trails the group being applied, or later groups'
                    # records would be late against their own poll's
                    # final watermark (the reference applies the whole
                    # burst before the periodic watermark advances)
                    g_wm = min(
                        td.to_ms(int(g_ticks.max())) - ooo_ms - 1, wm_ms
                    )
                # BETWEEN-polls time jump: if this group's panes sit
                # ahead of everything the ring has absorbed, applying
                # them could rotate the ring past still-unfired panes
                # — fire those panes' windows FIRST. (The catch-up
                # slicing above only bounds the span WITHIN one poll;
                # a quiet source resuming after an event-time gap —
                # or a processing-time job resuming after a
                # compile/GC pause — jumps between polls instead.)
                # The pre-fire watermark is capped at the group's min
                # pane boundary: a window ending there or earlier
                # receives NOTHING from this group, so firing it
                # before the update cannot split a window's records
                # across two emissions; capping at g_wm keeps the
                # watermark contract (nothing past the out-of-
                # orderness horizon closes early). Every pane the
                # rotation can evict ends all its windows below BOTH
                # caps — by the span bound above and the ring's
                # ooo-panes headroom (setup()) — so eviction only
                # ever discards already-fired state. Threshold 2:
                # steady-state polls advance at most one pane, so the
                # hot path never pays an extra drain.
                g_max_pane = int(g_ticks.max()) // int(win.slide_ticks)
                if (
                    applied_max_pane is not None
                    and g_max_pane - applied_max_pane >= 2
                ):
                    g_min_pane = (
                        int(g_ticks.min()) // int(win.slide_ticks)
                    )
                    fire_wm = min(
                        g_wm,
                        td.to_ms(g_min_pane * int(win.slide_ticks)) - 1,
                    )
                    drain_fires(fire_wm, time.perf_counter())
                applied_max_pane = (
                    g_max_pane if applied_max_pane is None
                    else max(applied_max_pane, g_max_pane)
                )
                # a host chain (flat_map) can expand one poll beyond B
                # lanes; feed the step in B-sized chunks padded to the
                # step lane count (B_step > B only when the exchange
                # splits lanes over shards). The watermark rides only
                # the LAST chunk so every record of the poll is
                # late-checked against the pre-poll watermark.
                Bs = B_step[0]
                for off in range(0, m, B):
                    hi_off = min(off + B, m)
                    chunk = (
                        _pad(g_hi[off:hi_off], Bs, np.uint32),
                        _pad(g_lo[off:hi_off], Bs, np.uint32),
                        _pad(g_ticks[off:hi_off], Bs, np.int32),
                        _pad(g_vals[off:hi_off], Bs, g_vals.dtype),
                        # reused prefix-mask template: a frozen view,
                        # not a per-chunk np.ones+pad allocation
                        ingest_mod.prefix_mask(
                            valid_tmpl[0], hi_off - off
                        ),
                    )
                    wm_chunk = g_wm if hi_off == m else None
                    if graph is not None:
                        # no single-step kernel exists for a stage
                        # chain: catch-up chunks ride the chained drain
                        # as 1-slot dispatches on the replicate-and-
                        # mask route (unrouted host arrays)
                        c_args, _ = _stage_planned(chunk, "mask")
                        run_update_resident(
                            "mask", [(c_args, wm_chunk, None)]
                        )
                    else:
                        run_update(*chunk, wm_chunk)
                # catch-up slices must fire between groups or newer
                # panes would evict older unfired ones from the ring
                if catch_up:
                    drain_fires(g_wm, time.perf_counter())
            if eager_fire or wp > host_fired_pane:
                drain_fires(wm_ms, time.perf_counter())
                host_fired_pane = wp

        # -- run with restore + restart (ref ExecutionGraph.restart + ------
        # -- CheckpointCoordinator.restoreLatestCheckpointedState) ---------
        # go live BEFORE restore: once td/state exist, a direct kv_read off
        # the executor thread would race the first donated step
        job_live.set()
        if wd is not None:
            wd.start()

        @contextlib.contextmanager
        def _restore_guard():
            """Watchdog bracket for a whole restore: the dedicated
            ``restore`` deadline (watchdog.restore-timeout) arms and the
            steady-state phase deadlines are suspended, so a
            legitimately long cold restore cannot trip a false
            WatchdogError mid-recovery."""
            if wd is None:
                yield
                return
            prev = wd.arm("restore")
            wd.suspend()
            try:
                yield
            finally:
                wd.unsuspend()
                wd.disarm(prev)

        def _elastic_replan(loss):
            """Degraded-mode recovery for a classified device loss:
            re-slice key-group ranges over the M surviving shards,
            rebuild the mesh + compiled step family, and perform a
            RESCALED restore of the last durable cut (the logical
            snapshot format re-buckets entries by key group, so the
            restore is parallelism-agnostic by construction). A loss
            without an attributable casualty (marker-matched runtime
            error, healthy probe) falls back to a same-parallelism full
            restore; survivors below recovery.min-shards FAIL the job
            (ElasticCapacityError — retrying cannot grow the mesh)."""
            t_replan0 = time.perf_counter()
            with rec_tracker.phase("reslice"):
                cur = list(np.asarray(ctx.mesh.devices).flat)
                survivors, newly = elastic.plan_survivors(cur, loss)
                if not newly:
                    survivors = None   # unattributable: same-mesh restore
                elif len(survivors) < elastic_min_shards:
                    raise elastic.ElasticCapacityError(
                        f"device loss leaves {len(survivors)} surviving "
                        f"shard(s), below recovery.min-shards="
                        f"{elastic_min_shards}; failing the job instead "
                        f"of degrading further"
                    ) from loss
                else:
                    n_before = ctx.n_shards
                    _replan_mesh(survivors)
            if survivors is None:
                with _restore_guard():
                    restore_checkpoint(storage, warm=False)
                return
            t0 = time.perf_counter()
            try:
                with _restore_guard():
                    restore_checkpoint(storage, warm=False)
            finally:
                rec_tracker.mark_phase("rescale_restore", t0)
            # restore_checkpoint stamped mode "full"; the re-plan is the
            # headline — restate it with the shard transition. The
            # controller records first so the tracker's degraded gauge
            # derives from it (one source of truth for the count).
            rec_tracker.set_mode(
                f"rescale-{ctx.n_shards}of{elastic_ctl.full_shards}"
            )
            elastic_ctl.record(
                "degrade", n_before, ctx.n_shards,
                cause=f"{type(loss).__name__}: {loss}", lost=newly,
                mttr_ms=(time.perf_counter() - t_replan0) * 1e3,
            )
            rec_tracker.note_rescale(
                n_before, ctx.n_shards, elastic_ctl.degraded_shards
            )

        def _recover(first_exc):
            """One failure -> a restored, runnable job, or raise.
            Classifies the failure (transient host-side -> warm
            in-process restart; device loss -> elastic re-plan over the
            survivors; anything else -> full restore), and keeps a
            failure DURING restore inside the restart budget: a double
            fault consumes another should_restart() slot and retries
            with the warm path disabled (the half-restored state is no
            longer trusted), instead of escaping as an unhandled error
            or wedging the job."""
            exc = first_exc
            warm = classify_failure(first_exc) == "transient"
            while True:
                loss = (
                    elastic.as_device_loss(
                        exc, devices=list(np.asarray(ctx.mesh.devices).flat)
                    )
                    if elastic_enabled else None
                )
                rec_tracker.begin(
                    cause=f"{type(exc).__name__}: {exc}",
                    classification=(
                        "device-loss" if loss is not None
                        else "transient" if warm else "state-corrupting"
                    ),
                )
                with rec_tracker.phase("settle"):
                    if materializer is not None:
                        # let pending async cuts become durable before
                        # deciding whether a restartable checkpoint
                        # exists
                        ck_io.settle()
                can = (
                    storage is not None
                    and storage.latest() is not None
                )
                if can:
                    with rec_tracker.phase("backoff"):
                        can = restart.should_restart()
                if not can:
                    raise exc
                metrics.restarts += 1
                self._notify_restart()
                try:
                    if loss is not None:
                        _elastic_replan(loss)
                    else:
                        with _restore_guard():
                            restore_checkpoint(storage, warm=warm)
                    rec_tracker.end()
                    return
                except JobCancelledException:
                    raise
                except elastic.ElasticCapacityError:
                    # deliberately NOT retried: the surviving device
                    # set cannot grow by restoring again
                    raise
                except Exception as e2:
                    exc, warm = e2, False

        try:
            if restore_from:
                rec_tracker.begin(cause="explicit restore_from",
                                  classification="initial")
                with _restore_guard():
                    restore_checkpoint(restore_from)
                rec_tracker.end()
            restart = self._restart_strategy()
            while True:
                try:
                    batch_loop()
                    # end of stream: MAX watermark flush (ref Watermark.
                    # MAX_WATERMARK). INSIDE the restart protection: a
                    # sink failing during the final flush must recover
                    # like any mid-stream failure — restore rewinds state,
                    # source offsets, and sink state to the checkpoint
                    # cut, so the re-run re-emits without duplication.
                    if td is not None:
                        drain_fires(int(td.to_ms(2**31 - 4)),
                                    time.perf_counter())
                    if materializer is not None:
                        # an async write still failing here IS a
                        # checkpoint failure: abort-and-count like any
                        # other; only budget exhaustion raises (inside
                        # the restart protection, so recovery treats it
                        # as one) — a transient final-write failure must
                        # not fail a job whose stream already completed
                        try:
                            ck_io.flush()
                        except MaterializerError as e:
                            _abort_checkpoint(
                                next_cid, e, time.perf_counter(),
                                time.time() * 1000,
                            )
                    break
                except JobCancelledException:
                    raise
                except Exception as e:
                    _recover(e)
        finally:
            if wd is not None:
                wd.stop()
            job_live.clear()
            ingest.close()
            drain_kv_mailbox()
            ck_io.close()

        if state is not None:
            # chained jobs fold every stage's counters in: an undersized
            # inter-stage exchange (pipeline.stages.exchange-lanes)
            # lands its drops in the DOWNSTREAM stage's
            # dropped_capacity, so strict capacity surfaces it loudly
            all_states = [state] + list(chain_states)
            metrics.dropped_late = sum(
                int(np.asarray(s.dropped_late).sum()) for s in all_states
            )
            metrics.dropped_capacity = sum(
                int(np.asarray(s.dropped_capacity).sum())
                for s in all_states
            )
            if metrics.dropped_capacity and self.env.config.get_bool(
                "state.backend.strict-capacity", True
            ):
                raise RuntimeError(
                    f"state backend over capacity: {metrics.dropped_capacity} "
                    f"records lost (raise state.backend.device.slots-per-shard "
                    f"or the pane ring — for chained stage graphs also "
                    f"pipeline.stages.exchange-lanes — or set "
                    f"state.backend.strict-capacity to false to tolerate "
                    f"drops)"
                )
        return JobHandle(job_name, metrics, state=state, ctx=ctx)

    # ------------------------------------------------------------------
    def _prep_keyed_batch(self, pipe: _Pipeline, polled, extractor):
        """Shared poll -> (key_list, values) prep for keyed stages without
        event-time handling (rolling / count windows)."""
        if pipe.source.columnar and isinstance(polled, tuple):
            cols, _ts = polled
            if not cols:
                return None
            for t in pipe.pre_chain:
                if t.kind != "map":
                    raise NotImplementedError(
                        "columnar sources support only 'map' before key_by"
                    )
                cols = t.fn(cols)
            return np.asarray(pipe.key_by.key_selector(cols)), np.asarray(
                extractor(cols)
            )
        elements = _apply_chain(pipe.pre_chain, self._to_elements(polled))
        if not elements:
            return None
        key_list = [pipe.key_by.key_selector(e) for e in elements]
        values = np.asarray([extractor(e) for e in elements], np.float32)
        return key_list, values

    def _run_generic_window(self, pipe: _Pipeline, metrics: JobMetrics,
                            job_name, restore_from=None):
        """Windows with custom triggers/evictors/apply functions or
        GlobalWindows: wrap into the GenericWindowOperator (full
        WindowOperator.java semantics) and drive it as a process stage."""
        from flink_tpu.datastream.window import triggers as tg
        from flink_tpu.datastream.window.assigners import (
            CountWindowAssigner, GlobalWindows,
        )
        from flink_tpu.runtime.window_operator import GenericWindowOperator
        from flink_tpu.state.descriptors import ReducingStateDescriptor

        wagg = pipe.window_agg
        assigner, trigger = wagg.assigner, wagg.trigger
        if isinstance(assigner, CountWindowAssigner):
            # countWindow(N) IS GlobalWindows + PurgingTrigger(CountTrigger)
            # (ref KeyedStream.countWindow); the device count path handles
            # the plain case, this lowering covers custom trigger/evictor/
            # apply combinations
            if trigger is None:
                trigger = tg.PurgingTrigger(tg.CountTrigger(assigner.size_n))
            assigner = GlobalWindows.create()
        reduce_desc = None
        if wagg.reduce_spec_factory is not None:
            spec = wagg.reduce_spec_factory()
            if spec.kind == "sketch":
                # host mirror of the device sketch registers: the element
                # folds in via host_add, sessions merge via host_merge, and
                # the fire emits host_result (estimates)
                from flink_tpu.state.descriptors import (
                    AggregatingStateDescriptor,
                )
                sk_obj = spec.sketch
                reduce_desc = AggregatingStateDescriptor(
                    "window-contents",
                    add=sk_obj.host_add, merge=sk_obj.host_merge,
                    get_result=sk_obj.host_result,
                    acc_init=sk_obj.host_init,
                )
            else:
                reduce_desc = ReducingStateDescriptor(
                    "window-contents", kind=spec.kind,
                    reduce_fn=spec.combine, neutral=spec.neutral,
                )
        op = GenericWindowOperator(
            assigner=assigner,
            trigger=trigger,
            evictor=wagg.evictor,
            extractor=wagg.extractor,
            reduce_desc=reduce_desc,
            window_fn=wagg.window_fn,
            allowed_lateness_ms=wagg.allowed_lateness_ms,
            result_fn=wagg.result_fn,
        )
        proc_pipe = dataclasses.replace(
            pipe, window_agg=None,
            process=sg.ProcessTransformation("generic-window", None, fn=op),
        )
        handle = self._run_process(proc_pipe, metrics, job_name, restore_from)
        metrics.dropped_late += op.dropped_late
        metrics.fires += op.fires
        return handle

    def _cep_device_eligible(self, pipe: _Pipeline, restore_from) -> bool:
        """Route CEP.pattern() to the TPU-resident count-NFA kernel when
        the pattern fits its representation (VERDICT r2 item 3; ref
        NFA.java:132 in production position, BASELINE config #5).

        Host-NFA fallback (the generality path) only when
        cep.device.enabled=false (the explicit escape hatch, e.g. for
        millisecond-exact within() boundaries) or an event-time job has
        no timestamp assigner. within() runs on device since round 4
        (pane-bucketed partial expiry, cep/device.py; semantics equal
        the host NFA on pane-quantized timestamps); EVENT TIME runs on
        device since round 5 (a host reorder buffer releases the
        watermark-ripe prefix in timestamp order into the device NFA —
        the buffer-and-sort the reference does per key, done once
        globally); parallelism>1 shards the count-NFA state over the
        mesh by key group (DeviceCepOperator n_shards). Checkpoint/
        savepoint/restore
        and queryable state are supported on the device path (parity
        with _run_process); a checkpoint written by one path cannot be
        restored by the other (validated, clear error). The engine that
        actually ran is surfaced in JobMetrics.cep_engine and the job
        detail JSON ("cep-engine")."""
        from flink_tpu.cep.operator import CEPProcessFunction

        fn = pipe.process.fn
        ok = (
            isinstance(fn, CEPProcessFunction)
            and self.env.config.get_bool("cep.device.enabled", True)
            # event-time (round 5): supported via the host reorder buffer
            # in front of the device kernel — needs element timestamps
            and (not fn.event_time or pipe.ts_transform is not None)
        )
        if ok and restore_from:
            # route by what the checkpoint actually contains: a host-path
            # checkpoint of a (now) device-eligible job must restore on
            # the host path, not die with a payload-kind error
            try:
                st = ckpt.CheckpointStorage(restore_from)
                cid = st.latest()
                if cid is not None:
                    return bool(st.read_generic(cid).get("cep_device"))
            except (OSError, ValueError):
                pass
        return ok

    def _run_cep_device(self, pipe: _Pipeline, metrics: JobMetrics,
                        job_name, restore_from=None):
        """Device CEP: per micro-batch, vectorized stage masks + the
        segmented-matrix-scan count NFA on device decide WHICH keys
        completed matches; the host replays only those keys' compacted
        events for extraction (cep/accel.py)."""
        from flink_tpu.cep.accel import DeviceCepOperator

        env = self.env
        fn = pipe.process.fn
        metrics.cep_engine = "device"
        n_shards = max(1, min(env.parallelism, len(jax.devices())))
        op = DeviceCepOperator(
            fn.pattern,
            capacity=env.state_capacity_per_shard or (1 << 16),
            within_buckets=env.config.get_int(
                "cep.device.within-buckets", 8
            ),
            # parallelism > 1: key-group shards over the mesh
            # (replicate-and-mask; VERDICT r3 item 6 multi-shard)
            n_shards=n_shards,
            max_parallelism=env.max_parallelism,
        )
        key_selector = pipe.key_by.key_selector
        select_fn = fn.select_fn
        flat = fn.flat

        # -- event-time mode (round 5): the reference buffers per key and
        # drains in timestamp order at watermark advance
        # (AbstractKeyedCEPPatternOperator's PriorityQueue). Here ONE
        # host-side reorder buffer fronts the device kernel: arrivals
        # heap-push as (ts, seq); each watermark advance releases the
        # ripe prefix GLOBALLY sorted (which preserves every key's
        # timestamp order) and feeds it to the device NFA in pane-sized
        # groups, so within() pane bucketing sees event time. Detection
        # stays on device; the host only sorts.
        import heapq

        event_time = fn.event_time
        ts_fn = (pipe.ts_transform.timestamp_fn
                 if pipe.ts_transform is not None else None)
        wm_strategy = (
            pipe.ts_transform.strategy if pipe.ts_transform is not None
            else WatermarkStrategy.for_monotonous_timestamps()
        )
        et_heap: list = []     # (ts, seq, key, element)
        et_seq = 0
        pane_ms = getattr(op.spec, "pane_ms", 0) or 0

        def _release(bound):
            out = []
            while et_heap and et_heap[0][0] <= bound:
                out.append(heapq.heappop(et_heap))
            return out

        def _feed_released(rel):
            """Feed timestamp-ordered released events to the device op,
            grouped by within() pane (without within, one group), in
            FIXED batch_size-padded chunks. A variable pad
            (ceil(n/bs)*bs) would give every release size its own XLA
            shape — profiled at 13 distinct compiles eating 75% of the
            event-time CEP run; one fixed shape compiles once."""
            matches = []
            bs = max(1, env.batch_size)
            i = 0
            while i < len(rel):
                if pane_ms:
                    p0 = rel[i][0] // pane_ms
                    j = i + 1
                    while j < len(rel) and rel[j][0] // pane_ms == p0:
                        j += 1
                else:
                    j = len(rel)
                for off in range(i, j, bs):
                    hi_off = min(off + bs, j)
                    els = [r[3] for r in rel[off:hi_off]]
                    ks = [r[2] for r in rel[off:hi_off]]
                    matches += op.process_batch(
                        els, ks, int(rel[off][0]), pad_to=bs,
                    )
                    metrics.steps += 1
                i = j
            return matches

        reg = getattr(env, "_kv_registry", None)
        if reg is not None:
            # host-path parity: the per-key live partial matches are
            # queryable under the same name _run_process registers
            reg.register_resolver(
                lambda: ["cep-nfa-state"],
                lambda name, key: op.peek_state(key),
            )

        storage = None
        if env.checkpoint_dir:
            # task-local snapshot cache (checkpointing/local.py): publish
            # mirrors in, restore prefers the verified local copy
            storage = ckpt.CheckpointStorage(
                env.checkpoint_dir,
                retain=env.config.get_int("checkpoint.retain", 2),
                local=local_cache_from_config(
                    env.config, env.checkpoint_dir
                ),
            )
        next_cid = (storage.latest() or 0) + 1 if storage else 1
        steps_at_ckpt = 0
        ck_policy = policy_from_config(env.config) if storage is not None \
            else None
        metrics.failure_budget = ck_policy
        ck_io = _GenericCheckpointIO(env, storage, pipe, policy=ck_policy)

        def _payload():
            return {
                "cep_device": True,
                "event_time": event_time,
                "op": op.snapshot(),
                "offsets": pipe.source.snapshot_offsets(),
                "sink_states": [s.snapshot_state() for s in pipe.all_sinks],
                # event-time reorder buffer: ripe-but-unreleased events
                # are part of the cut (the host path snapshots its
                # per-key PriorityQueue the same way)
                "et_heap": list(et_heap),
                "et_seq": et_seq,
                "wm_current": wm_strategy.current(),
            }

        def write_checkpoint():
            nonlocal next_cid, steps_at_ckpt
            _guarded_generic_write(
                ck_io, ck_policy, storage, metrics, next_cid, _payload
            )
            next_cid += 1
            steps_at_ckpt = metrics.steps

        def restore_checkpoint(path_or_storage, cid=None):
            nonlocal steps_at_ckpt, et_heap, et_seq
            ck_io.recover()           # durable cuts still notify
            st = (
                ckpt.CheckpointStorage(path_or_storage)
                if isinstance(path_or_storage, str) else path_or_storage
            )
            cid = cid if cid is not None else st.latest()
            if cid is None:
                raise FileNotFoundError(f"no checkpoint in {st.dir}")
            payload = st.read_generic(cid)
            if not payload.get("cep_device"):
                raise ValueError(
                    "checkpoint was written by the host CEP path; restore "
                    "it with the same configuration (event-time/within/"
                    "parallelism) it was created under"
                )
            if bool(payload.get("event_time")) != event_time:
                raise ValueError(
                    "checkpoint time mode (event-time vs processing-"
                    "time) does not match the job configuration"
                )
            op.restore(payload["op"])
            pipe.source.restore_offsets(payload["offsets"])
            sink_states = payload.get("sink_states")
            if sink_states:
                for s, ss in zip(pipe.all_sinks, sink_states):
                    s.restore_state(ss)
            et_heap = [tuple(x) for x in payload.get("et_heap", [])]
            heapq.heapify(et_heap)
            et_seq = int(payload.get("et_seq", 0))
            wm_strategy._current = payload.get(
                "wm_current", wm_strategy.current()
            )
            steps_at_ckpt = metrics.steps

        def write_savepoint(path: str) -> str:
            sp = ckpt.CheckpointStorage(path, retain=10**9)
            cid = (sp.latest() or 0) + 1
            return sp.write_generic(cid, _payload())

        self._savepoint_writer = write_savepoint

        def batch_loop():
            nonlocal et_seq
            end = False
            n_batches = 0
            while not end:
                self._poll_control()
                n_batches += 1
                polled, end = pipe.source.poll(env.batch_size)
                elements = _apply_chain(pipe.pre_chain,
                                        self._to_elements(polled))
                if not elements:
                    if end and event_time and et_heap:
                        # end of stream: everything still buffered is
                        # ripe (the MAX-watermark drain)
                        matches = _feed_released(_release(2**62))
                        if matches:
                            out = (
                                [r for m in matches for r in
                                 select_fn(m)] if flat
                                else [select_fn(m) for m in matches]
                            )
                            _emit_batch(pipe, out, metrics)
                    continue
                metrics.records_in += len(elements)
                keys = [key_selector(e) for e in elements]
                if event_time:
                    ts_list = [int(ts_fn(e)) for e in elements]
                    for e, k, t in zip(elements, keys, ts_list):
                        heapq.heappush(et_heap, (t, et_seq, k, e))
                        et_seq += 1
                    wm = wm_strategy.on_batch(max(ts_list))
                    matches = _feed_released(
                        _release(2**62 if end else wm)
                    )
                else:
                    now_ms = int(time.time() * 1000)
                    # pre-chain ops (flat_map) can expand past
                    # batch_size: pad to the next batch_size multiple
                    # (small jit cache)
                    bs = max(1, env.batch_size)
                    pad = ((len(elements) + bs - 1) // bs) * bs
                    matches = op.process_batch(elements, keys, now_ms,
                                               pad_to=pad)
                    metrics.steps += 1
                if n_batches % 64 == 0:
                    # bound host buffers to live-partial size (a BATCH
                    # counter: event-time releases can take several device
                    # steps per batch, so metrics.steps may stride over
                    # any fixed modulus); any matches surfacing here
                    # indicate a count/extraction skew — emit rather than
                    # swallow (but never clobber the batch's own matches,
                    # still pending below)
                    pruned = op.prune_dead_keys()
                    if pruned:
                        out = ([r for m in pruned for r in select_fn(m)]
                               if flat else [select_fn(m) for m in pruned])
                        _emit_batch(pipe, out, metrics)
                if matches:
                    if flat:
                        out = [r for m in matches for r in select_fn(m)]
                    else:
                        out = [select_fn(m) for m in matches]
                    _emit_batch(pipe, out, metrics)
                ck_io.drain()
                if (
                    storage is not None
                    and env.checkpoint_interval_steps > 0
                    and metrics.steps - steps_at_ckpt
                    >= env.checkpoint_interval_steps
                ):
                    write_checkpoint()

        if restore_from:
            restore_checkpoint(restore_from)
        restart = self._restart_strategy()
        try:
            while True:
                try:
                    batch_loop()
                    ck_io.flush()
                    break
                except JobCancelledException:
                    raise
                except Exception:
                    ck_io.settle()
                    can = (
                        storage is not None
                        and storage.latest() is not None
                        and restart.should_restart()
                    )
                    if not can:
                        raise
                    metrics.restarts += 1
                    self._notify_restart()
                    restore_checkpoint(storage)
        finally:
            ck_io.close()

        # end of stream: live partials simply die (a CEP match emits the
        # moment it completes; there is no pending-fire flush)
        metrics.cep_device_steps = op.steps
        metrics.cep_matches_detected = op.matches_detected
        metrics.cep_matches_extracted = op.matches_extracted
        metrics.dropped_capacity += op.dropped_capacity
        return JobHandle(job_name, metrics)

    def _run_process(self, pipe: _Pipeline, metrics: JobMetrics, job_name,
                     restore_from=None):
        """Keyed ProcessFunction stage: host generality path over the heap
        keyed backend + internal timer service (ref StreamTimelyFlatMap /
        KeyedProcessOperator). Hot aggregations belong on the device stages;
        this path exists for arbitrary user logic and semantics parity."""
        from flink_tpu.core.time import TimeCharacteristic
        from flink_tpu.cep.operator import CEPProcessFunction
        from flink_tpu.datastream.functions import (
            Collector, OnTimerContext, ProcessContext, RichFunction,
            RuntimeContext, TimerService,
        )
        from flink_tpu.runtime.timers import InternalTimerService
        from flink_tpu.state.backend import HeapKeyedStateBackend

        if isinstance(pipe.process.fn, CEPProcessFunction):
            metrics.cep_engine = "host"

        env = self.env
        fn = pipe.process.fn
        event_time = env.time_characteristic == TimeCharacteristic.EventTime
        backend = HeapKeyedStateBackend(max_parallelism=env.max_parallelism)
        backend.serializer_registry = env.serializer_registry
        timers = InternalTimerService(env.max_parallelism)
        collector = Collector()
        timer_svc = TimerService(timers, lambda: backend.current_key)
        ctx = ProcessContext(timer_svc)
        timer_ctx = OnTimerContext(timer_svc)

        class _Triggerable:
            def _fire(self, timer, domain):
                backend.set_current_key(timer.key)
                timer_ctx.key = timer.key
                timer_ctx.namespace = timer.namespace
                timer_ctx.time_domain = domain
                timer_ctx.element_timestamp = timer.timestamp
                fn.on_timer(timer.timestamp, timer_ctx, collector)

            def on_event_time(self, timer):
                self._fire(timer, "event")

            def on_processing_time(self, timer):
                self._fire(timer, "processing")

        timers.triggerable = _Triggerable()
        if hasattr(fn, "bind_internals"):
            # operators needing namespaced timers/state (GenericWindowOperator)
            fn.bind_internals(backend, timers)
        reg = getattr(env, "_kv_registry", None)
        from flink_tpu.core.accumulators import AccumulatorRegistry
        from flink_tpu.state.operator_state import OperatorStateStore

        accumulators = AccumulatorRegistry()
        operator_state = OperatorStateStore()
        if isinstance(fn, RichFunction):
            fn.open(RuntimeContext(
                backend,
                metrics_group=(
                    self._job_group.add_group("user")
                    if self._job_group is not None else None
                ),
                accumulators=accumulators,
                operator_state=operator_state,
            ))
        if reg is not None:
            # resolve against the backend's live table set at query time so
            # states created lazily on the first record are queryable too,
            # not only those created in open() (ref KvStateRegistry)
            reg.register_resolver(
                lambda: list(backend._tables),
                lambda n, key: backend.lookup(n, key),
            )

        wm_strategy = (
            pipe.ts_transform.strategy if pipe.ts_transform is not None
            else WatermarkStrategy.for_monotonous_timestamps()
        )

        storage = None
        if env.checkpoint_dir:
            # task-local snapshot cache (checkpointing/local.py): publish
            # mirrors in, restore prefers the verified local copy
            storage = ckpt.CheckpointStorage(
                env.checkpoint_dir,
                retain=env.config.get_int("checkpoint.retain", 2),
                local=local_cache_from_config(
                    env.config, env.checkpoint_dir
                ),
            )
        next_cid = (storage.latest() or 0) + 1 if storage else 1
        steps_at_ckpt = 0
        ck_policy = policy_from_config(env.config) if storage is not None \
            else None
        metrics.failure_budget = ck_policy
        ck_io = _GenericCheckpointIO(env, storage, pipe, policy=ck_policy)

        def write_checkpoint():
            nonlocal next_cid, steps_at_ckpt
            _guarded_generic_write(
                ck_io, ck_policy, storage, metrics, next_cid,
                lambda: {
                    "backend": backend.snapshot(),
                    "timers": timers.snapshot(),
                    "offsets": pipe.source.snapshot_offsets(),
                    "wm_current": wm_strategy.current(),
                    "proc_time": timers.current_processing_time,
                    "max_parallelism": env.max_parallelism,
                    "sink_states": [
                        s.snapshot_state() for s in pipe.all_sinks
                    ],
                    "accumulators": accumulators.snapshot(),
                    "operator_state": operator_state.snapshot(),
                },
            )
            next_cid += 1
            steps_at_ckpt = metrics.steps

        def restore_checkpoint(path_or_storage, cid=None):
            nonlocal steps_at_ckpt
            ck_io.recover()           # durable cuts still notify
            st = (
                ckpt.CheckpointStorage(path_or_storage)
                if isinstance(path_or_storage, str) else path_or_storage
            )
            cid = cid if cid is not None else st.latest()
            if cid is None:
                raise FileNotFoundError(f"no checkpoint in {st.dir}")
            payload = st.read_generic(cid)
            if payload.get("cep_device"):
                raise ValueError(
                    "checkpoint was written by the device CEP path; "
                    "restoring it requires a device-eligible configuration "
                    "(no within(), processing time, parallelism 1)"
                )
            if payload["max_parallelism"] != env.max_parallelism:
                raise ValueError("checkpoint max-parallelism mismatch")
            backend.restore(payload["backend"])
            # restore throws away pending queues; re-register from snapshot
            timers._event_q.clear(); timers._proc_q.clear()
            timers._event_set.clear(); timers._proc_set.clear()
            timers.restore(payload["timers"])
            pipe.source.restore_offsets(payload["offsets"])
            sink_states = payload.get("sink_states")
            if sink_states:
                if len(sink_states) != len(pipe.all_sinks):
                    raise ValueError(
                        f"checkpoint has {len(sink_states)} sink states but "
                        f"the job topology has {len(pipe.all_sinks)} sinks — "
                        f"restore with the matching pipeline"
                    )
                for s, ss in zip(pipe.all_sinks, sink_states):
                    s.restore_state(ss)
            wm_strategy._current = payload["wm_current"]
            timers.current_watermark = payload["wm_current"]
            timers.current_processing_time = payload.get(
                "proc_time", timers.current_processing_time
            )
            # roll accumulators + operator state back to the cut: the
            # replayed records re-apply their contributions exactly once
            accumulators.restore(payload.get("accumulators", {}))
            operator_state.restore(payload.get("operator_state", {}))
            steps_at_ckpt = metrics.steps

        def write_savepoint(path: str) -> str:
            sp = ckpt.CheckpointStorage(path, retain=10**9)
            cid = (sp.latest() or 0) + 1
            return sp.write_generic(cid, {
                "backend": backend.snapshot(),
                "timers": timers.snapshot(),
                "offsets": pipe.source.snapshot_offsets(),
                "wm_current": wm_strategy.current(),
                "proc_time": timers.current_processing_time,
                "max_parallelism": env.max_parallelism,
                "sink_states": [s.snapshot_state() for s in pipe.all_sinks],
                "accumulators": accumulators.snapshot(),
                "operator_state": operator_state.snapshot(),
            })

        self._savepoint_writer = write_savepoint

        def emit():
            out = collector.drain()
            if not out:
                return
            _emit_batch(pipe, out, metrics)

        def batch_loop():
            end = False
            while not end:
                self._poll_control()
                polled, end = pipe.source.poll(env.batch_size)
                now_ms = int(time.time() * 1000)
                # sync the clock BEFORE elements see it: triggers compute
                # interval timers from current_processing_time, and the
                # -2^62 sentinel would put those timers ~2^62 in the past
                # (a ~1e15-iteration advance cascade)
                if timers.current_processing_time < now_ms:
                    timers.current_processing_time = now_ms
                elements = _apply_chain(
                    pipe.pre_chain, self._to_elements(polled)
                )
                metrics.records_in += len(elements)
                for e in elements:
                    key = pipe.key_by.key_selector(e)
                    backend.set_current_key(key)
                    if event_time and pipe.ts_transform is not None:
                        ctx.element_timestamp = int(
                            pipe.ts_transform.timestamp_fn(e)
                        )
                    else:
                        ctx.element_timestamp = now_ms
                    fn.process_element(e, ctx, collector)
                metrics.steps += 1
                if event_time:
                    ts_list = None
                    if elements and pipe.ts_transform is not None:
                        ts_list = max(
                            int(pipe.ts_transform.timestamp_fn(e))
                            for e in elements
                        )
                    wm = wm_strategy.on_batch(ts_list)
                    timers.advance_watermark(wm)
                else:
                    timers.advance_processing_time(now_ms)
                emit()
                ck_io.drain()
                if (
                    storage is not None
                    and env.checkpoint_interval_steps > 0
                    and metrics.steps - steps_at_ckpt
                    >= env.checkpoint_interval_steps
                ):
                    write_checkpoint()

        if restore_from:
            restore_checkpoint(restore_from)
        restart = self._restart_strategy()
        try:
            while True:
                try:
                    batch_loop()
                    ck_io.flush()
                    break
                except JobCancelledException:
                    raise
                except Exception:
                    ck_io.settle()
                    can = (
                        storage is not None
                        and storage.latest() is not None
                        and restart.should_restart()
                    )
                    if not can:
                        raise
                    metrics.restarts += 1
                    self._notify_restart()
                    collector.drain()  # discard partial output of failed run
                    restore_checkpoint(storage)
        finally:
            ck_io.close()

        # end of stream: flush everything pending (the device stages'
        # MAX-watermark flush analog; finite sources always drain). Single
        # pass: re-registered timers don't cascade.
        timers.drain(2**62)
        emit()
        if isinstance(fn, RichFunction):
            fn.close()
        return JobHandle(job_name, metrics, state=backend,
                         accumulator_results=accumulators.results())

    # ------------------------------------------------------------------
    def _run_rolling(self, pipe: _Pipeline, metrics: JobMetrics, job_name,
                     restore_from=None):
        """Rolling keyed reduce: emits the updated accumulator per record
        (ref StreamGroupedReduce)."""
        from flink_tpu.runtime.step import (
            RollingStageSpec, build_rolling_step, init_rolling_state,
        )

        env = self.env
        roll = pipe.rolling
        red = roll.reduce_spec_factory()
        n_dev = len(jax.devices())
        n_shards = max(1, min(env.parallelism, n_dev))
        ctx = MeshContext.create(n_shards, env.max_parallelism)
        spec = RollingStageSpec(
            red=red, capacity_per_shard=env.state_capacity_per_shard
        )
        step = build_rolling_step(ctx, spec)
        state = init_rolling_state(ctx, spec)
        B = env.batch_size
        # reused prefix-mask template (one allocation per stage; the
        # valid mask of each batch is a frozen view slice)
        valid_tmpl = ingest_mod.make_prefix_mask_template(B)
        keep_rev = env.config.get_bool("keys.reverse-map", True)
        codec = KeyCodec()

        def kv_query(key):
            """Queryable rolling accumulator (ref asQueryableState). The
            rolling step does NOT donate, so a single snapshot of the state
            reference yields a consistent pytree even while the job runs
            (reading `state` repeatedly could tear across a rebind)."""
            from flink_tpu.core.keygroups import assign_to_key_group
            from flink_tpu.ops.hashing import route_hash

            st = state
            hi, lo = codec.encode(
                np.asarray([key]) if np.isscalar(key) or isinstance(
                    key, (int, float)
                ) else [key],
                keep_reverse=False,
            )
            kg = int(assign_to_key_group(
                route_hash(hi, lo, np), ctx.max_parallelism, np
            )[0])
            shard = int(ctx.shard_of_key_groups(np.asarray([kg]))[0])
            tkeys = np.asarray(st.table.keys[shard])
            match = np.nonzero(
                (tkeys[:, 0] == hi[0]) & (tkeys[:, 1] == lo[0])
            )[0]
            if match.size == 0:
                return None
            slot = int(match[0])
            if not bool(np.asarray(st.touched[shard])[slot]):
                return None
            v = np.asarray(st.acc[shard])[slot]
            if roll.result_fn is not None:
                v = np.asarray(roll.result_fn(v))
            return v.tolist()

        reg = getattr(env, "_kv_registry", None)
        if reg is not None:
            reg.register(roll.name, kv_query)

        def emit_one(item):
            outputs, out_valid, klist, n = item
            out_np = np.asarray(outputs)[:n]
            ok_np = np.asarray(out_valid)[:n]
            if roll.result_fn is not None:
                out_np = np.asarray(roll.result_fn(out_np))
            out = [
                (k, v) for k, v, okv in zip(klist, out_np.tolist(), ok_np)
                if okv
            ]
            _emit_batch(pipe, out, metrics)

        emitter = _LaggedEmitter(env, emit_one)

        def _set_state(s):
            nonlocal state
            state = s

        ckptr = _FlatStageCheckpointer(
            self, pipe, ctx, codec, keep_rev, emitter, metrics,
            get_state=lambda: state, set_state=_set_state,
            stage_kind="rolling-reduce",
            meta={
                "capacity_per_shard": env.state_capacity_per_shard,
                "red_kind": red.kind,
            },
        )

        def batch_loop():
            nonlocal state
            end = False
            while not end:
                self._poll_control()
                polled, end = pipe.source.poll(B)
                prepped = self._prep_keyed_batch(pipe, polled,
                                                 roll.extractor)
                if prepped is None:
                    emitter.idle()  # idle source must not withhold results
                    continue
                key_list, values = prepped
                hi, lo = codec.encode(key_list, keep_reverse=keep_rev)
                n = len(hi)
                metrics.records_in += n
                state, outputs, out_valid = step(
                    state,
                    jnp.asarray(_pad(hi, B, np.uint32)),
                    jnp.asarray(_pad(lo, B, np.uint32)),
                    jnp.asarray(_pad(values, B, values.dtype)),
                    jnp.asarray(ingest_mod.prefix_mask(valid_tmpl, n)),
                )
                metrics.steps += 1
                klist = (
                    key_list.tolist() if isinstance(key_list, np.ndarray)
                    else key_list
                )
                emitter.push((outputs, out_valid, klist, n))
                ckptr.maybe_checkpoint()
            # end of stream INSIDE restart protection: a sink failing
            # during the final drain recovers like any mid-stream failure
            emitter.drain()

        ckptr.run_with_restarts(batch_loop, restore_from)

        dropped = int(np.asarray(state.dropped_capacity).sum())
        metrics.dropped_capacity = dropped
        if dropped and env.config.get_bool("state.backend.strict-capacity", True):
            raise RuntimeError(
                f"state backend over capacity: {dropped} records lost"
            )
        return JobHandle(job_name, metrics, state=state, ctx=ctx)

    # ------------------------------------------------------------------
    def _run_session(self, pipe: _Pipeline, metrics: JobMetrics, job_name,
                     restore_from=None):
        """Session windows with gap-based merging (see ops/session_windows)."""
        from flink_tpu.core.time import TimeCharacteristic
        from flink_tpu.runtime.step import (
            SessionStageSpec, build_session_step, init_session_state,
        )

        env = self.env
        wagg = pipe.window_agg
        assigner = wagg.assigner
        event_time = assigner.is_event_time and (
            env.time_characteristic == TimeCharacteristic.EventTime
        )
        red = wagg.reduce_spec_factory()
        n_dev = len(jax.devices())
        n_shards = max(1, min(env.parallelism, n_dev))
        ctx = MeshContext.create(n_shards, env.max_parallelism)
        spec = SessionStageSpec(
            red=red, gap_ticks=assigner.gap_ms,
            capacity_per_shard=env.state_capacity_per_shard,
        )
        step = build_session_step(ctx, spec)
        state = init_session_state(ctx, spec)
        B = env.batch_size
        # reused prefix-mask template (one allocation per stage; the
        # valid mask of each batch is a frozen view slice)
        valid_tmpl = ingest_mod.make_prefix_mask_template(B)
        keep_rev = env.config.get_bool("keys.reverse-map", True)
        codec = KeyCodec()
        td: Optional[TimeDomain] = None
        wm_strategy = (
            pipe.ts_transform.strategy if pipe.ts_transform is not None
            else WatermarkStrategy.for_monotonous_timestamps()
        )

        # lagged emission (_LaggedEmitter): fires + the step's table-key
        # handle are retained and read `lag` steps later, so the d2h read
        # overlaps subsequent dispatches. The session step does NOT donate
        # state, so the captured keys handle is an immutable snapshot.
        def emit(item):
            old_f, mid_f, wm_f, tkeys_handle = item
            out = []
            tkeys = np.asarray(tkeys_handle)
            for fire in (old_f, mid_f):
                khi, klo, f_start, f_end, f_vals, f_mask = map(np.asarray, fire)
                for sh in range(khi.shape[0]):
                    sel = np.nonzero(f_mask[sh])[0]
                    if not sel.size:
                        continue
                    keys = codec.decode(khi[sh, sel], klo[sh, sel])
                    for k, st_, en_, v in zip(
                        keys, f_start[sh, sel].tolist(),
                        f_end[sh, sel].tolist(), f_vals[sh, sel].tolist(),
                    ):
                        out.append(SessionResult(
                            k, int(td.to_ms(st_)), int(td.to_ms(en_)), v
                        ))
            w_start, w_end, w_vals, w_mask = map(np.asarray, wm_f)
            for sh in range(w_mask.shape[0]):
                sel = np.nonzero(w_mask[sh])[0]
                if not sel.size:
                    continue
                keys = codec.decode(tkeys[sh, sel, 0], tkeys[sh, sel, 1])
                for k, st_, en_, v in zip(
                    keys, w_start[sh, sel].tolist(),
                    w_end[sh, sel].tolist(), w_vals[sh, sel].tolist(),
                ):
                    out.append(SessionResult(
                        k, int(td.to_ms(st_)), int(td.to_ms(en_)), v
                    ))
            if not out:
                return
            if wagg.result_fn is not None:
                out = [r._replace(value=float(np.asarray(
                    wagg.result_fn(np.asarray(r.value))))) for r in out]
            metrics.fires += len(out)
            _emit_batch(pipe, out, metrics)

        emitter = _LaggedEmitter(env, emit)

        # -- checkpoint/restore: the shared flat-pytree machinery
        # (_FlatStageCheckpointer — round 4 introduced the session
        # support inline, round 5 unified it with rolling/count). The
        # session-specific non-array state (watermark + time-domain
        # origin) rides the payload's stage_extra hooks.
        def _set_state(s):
            nonlocal state
            state = s

        def _extra():
            return {
                "wm_current": wm_strategy.current(),
                "origin_ms": td.origin_ms if td is not None else None,
            }

        def _apply_extra(extra):
            nonlocal td
            wm_strategy._current = extra["wm_current"]
            if extra["origin_ms"] is not None:
                td = TimeDomain(origin_ms=extra["origin_ms"],
                                ms_per_tick=1)

        ckptr = _FlatStageCheckpointer(
            self, pipe, ctx, codec, keep_rev, emitter, metrics,
            get_state=lambda: state, set_state=_set_state,
            stage_kind="session-window",
            meta={
                "gap_ms": assigner.gap_ms,
                "capacity_per_shard": env.state_capacity_per_shard,
            },
            extra_payload=_extra, apply_extra=_apply_extra,
        )

        def run_once(hi, lo, ticks, values, valid, wm_ms):
            nonlocal state
            wmv = np.full((ctx.n_shards,), np.int32(   # numpy: eager tiny
                min(int(td.to_ticks(wm_ms)), 2**31 - 4)  # ops cost a full
                if wm_ms is not None else -(2**31) + 1    # tunnel round trip
            ))
            state, old_f, mid_f, wm_f = step(
                state, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(ticks),
                jnp.asarray(values), jnp.asarray(valid), wmv,
            )
            metrics.steps += 1
            emitter.push((old_f, mid_f, wm_f, state.table.keys))

        def batch_loop():
            nonlocal td
            end = False
            while not end:
                self._poll_control()
                polled, end = pipe.source.poll(B)
                now_ms = int(time.time() * 1000)
                if pipe.source.columnar and isinstance(polled, tuple):
                    cols, ts_ms = polled
                    if not cols:
                        emitter.idle()
                        continue
                    for t in pipe.pre_chain:
                        if t.kind != "map":
                            raise NotImplementedError(
                                "columnar sources support only 'map' "
                                "before key_by"
                            )
                        cols = t.fn(cols)
                    key_list = np.asarray(pipe.key_by.key_selector(cols))
                    values = np.asarray(wagg.extractor(cols))
                    if event_time and pipe.ts_transform is not None:
                        ts_ms = np.asarray(
                            pipe.ts_transform.timestamp_fn(cols), np.int64)
                    elif not event_time or ts_ms is None:
                        ts_ms = np.full(len(key_list), now_ms, np.int64)
                else:
                    elements = _apply_chain(pipe.pre_chain,
                                            self._to_elements(polled))
                    if not elements:
                        emitter.idle()
                        continue
                    key_list = [pipe.key_by.key_selector(e)
                                for e in elements]
                    values = np.asarray(
                        [wagg.extractor(e) for e in elements], np.float32
                    )
                    if event_time and pipe.ts_transform is not None:
                        ts_ms = np.asarray(
                            [pipe.ts_transform.timestamp_fn(e)
                             for e in elements],
                            np.int64,
                        )
                    else:
                        ts_ms = np.full(len(key_list), now_ms, np.int64)
                hi, lo = codec.encode(key_list, keep_reverse=keep_rev)
                n = len(hi)
                metrics.records_in += n
                if td is None:
                    td = TimeDomain(origin_ms=int(np.min(ts_ms)),
                                    ms_per_tick=1)
                ticks = td.to_ticks(ts_ms)
                wm_ms = (
                    wm_strategy.on_batch(int(np.max(ts_ms))) if event_time
                    else now_ms - 1
                )
                run_once(
                    _pad(hi, B, np.uint32), _pad(lo, B, np.uint32),
                    _pad(ticks, B, np.int32), _pad(values, B, np.float32),
                    ingest_mod.prefix_mask(valid_tmpl, n), wm_ms,
                )
                if td is not None:
                    ckptr.maybe_checkpoint()
            if td is not None:
                # end of stream: close all open sessions. INSIDE the
                # restart protection — a sink failing during the final
                # flush recovers like any mid-stream failure.
                final_wm = int(td.to_ms(2**31 - 4))
                run_once(
                    np.zeros(B, np.uint32), np.zeros(B, np.uint32),
                    np.zeros(B, np.int32),
                    np.zeros((B,) + tuple(red.value_shape), np.float32),
                    np.zeros(B, bool), final_wm,
                )
            emitter.drain()

        ckptr.run_with_restarts(batch_loop, restore_from)

        metrics.dropped_late = int(np.asarray(state.dropped_late).sum())
        dropped = int(np.asarray(state.dropped_capacity).sum())
        metrics.dropped_capacity = dropped
        if dropped and env.config.get_bool("state.backend.strict-capacity", True):
            raise RuntimeError(
                f"state backend over capacity: {dropped} records lost"
            )
        return JobHandle(job_name, metrics, state=state, ctx=ctx)

    # ------------------------------------------------------------------
    def _run_count(self, pipe: _Pipeline, metrics: JobMetrics, job_name,
                   restore_from=None):
        """countWindow(N): per-key tumbling windows of N elements."""
        from flink_tpu.runtime.step import (
            CountStageSpec, build_count_step, init_count_state,
        )

        env = self.env
        wagg = pipe.window_agg
        red = wagg.reduce_spec_factory()
        n_dev = len(jax.devices())
        n_shards = max(1, min(env.parallelism, n_dev))
        ctx = MeshContext.create(n_shards, env.max_parallelism)
        spec = CountStageSpec(
            red=red, n_per_window=wagg.assigner.size_n,
            capacity_per_shard=env.state_capacity_per_shard,
        )
        step = build_count_step(ctx, spec)
        state = init_count_state(ctx, spec)
        B = env.batch_size
        # reused prefix-mask template (one allocation per stage; the
        # valid mask of each batch is a frozen view slice)
        valid_tmpl = ingest_mod.make_prefix_mask_template(B)
        keep_rev = env.config.get_bool("keys.reverse-map", True)
        codec = KeyCodec()

        def emit_one(item):
            khi, klo, w, vals, mask = item
            mask_np = np.asarray(mask)
            if not mask_np.any():
                return
            khi_np = np.asarray(khi)[mask_np]
            klo_np = np.asarray(klo)[mask_np]
            w_np = np.asarray(w)[mask_np]
            v_np = np.asarray(vals)[mask_np]
            if wagg.result_fn is not None:
                v_np = np.asarray(wagg.result_fn(v_np))
            keys = codec.decode(khi_np, klo_np)
            out = [
                WindowResult(k, int(wi), vv)
                for k, wi, vv in zip(keys, w_np.tolist(), v_np.tolist())
            ]
            metrics.fires += len(out)
            _emit_batch(pipe, out, metrics)

        emitter = _LaggedEmitter(env, emit_one)

        def _set_state(s):
            nonlocal state
            state = s

        ckptr = _FlatStageCheckpointer(
            self, pipe, ctx, codec, keep_rev, emitter, metrics,
            get_state=lambda: state, set_state=_set_state,
            stage_kind="count-window",
            meta={
                "capacity_per_shard": env.state_capacity_per_shard,
                "red_kind": red.kind,
                "n_per_window": wagg.assigner.size_n,
            },
        )

        def batch_loop():
            nonlocal state
            end = False
            while not end:
                self._poll_control()
                polled, end = pipe.source.poll(B)
                prepped = self._prep_keyed_batch(pipe, polled,
                                                 wagg.extractor)
                if prepped is None:
                    emitter.idle()
                    continue
                key_list, values = prepped
                hi, lo = codec.encode(key_list, keep_reverse=keep_rev)
                n = len(hi)
                metrics.records_in += n
                state, khi, klo, w, vals, mask = step(
                    state,
                    jnp.asarray(_pad(hi, B, np.uint32)),
                    jnp.asarray(_pad(lo, B, np.uint32)),
                    jnp.asarray(_pad(values, B, values.dtype)),
                    jnp.asarray(ingest_mod.prefix_mask(valid_tmpl, n)),
                )
                metrics.steps += 1
                emitter.push((khi, klo, w, vals, mask))
                ckptr.maybe_checkpoint()
            emitter.drain()

        ckptr.run_with_restarts(batch_loop, restore_from)

        dropped = int(np.asarray(state.dropped_capacity).sum())
        metrics.dropped_capacity = dropped
        if dropped and env.config.get_bool("state.backend.strict-capacity", True):
            raise RuntimeError(
                f"state backend over capacity: {dropped} records lost"
            )
        return JobHandle(job_name, metrics, state=state, ctx=ctx)

    @staticmethod
    def _empty_step(run_step, B, red, wm_ms):
        hi = np.zeros(B, np.uint32)
        lo = np.zeros(B, np.uint32)
        ticks = np.zeros(B, np.int32)
        if red.kind == "sketch":
            values = np.zeros(B, np.uint32)  # per-record item hashes
        else:
            values = np.zeros((B,) + tuple(red.value_shape), np.float32)
        valid = np.zeros(B, bool)
        return run_step(hi, lo, ticks, values, valid, wm_ms)
