"""Internal timer service — per-(key, namespace) event/processing timers.

Mirrors the contracts of the reference's HeapInternalTimerService
(api/operators/HeapInternalTimerService.java:43: registerEventTimeTimer:212,
advanceWatermark:264, onProcessingTime:239) and SystemProcessingTimeService:
a priority queue + dedup set per time domain, fired in timestamp order with
the key context restored before each callback, snapshotted by key group.

TPU adaptation: callbacks run on the host between micro-batch steps (the
device analog of timers — pane deadlines — lives in ops/window_kernels; this
service backs the general ProcessFunction/trigger path). Processing time is
advanced explicitly by the executor (or a test clock), which is what the
reference's TestProcessingTimeService does in its harnesses.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from flink_tpu.state.backend import key_group_of


@dataclass(frozen=True, order=True)
class InternalTimer:
    """(timestamp, key, namespace) — ref InternalTimer.java."""

    timestamp: int
    key: Any = field(compare=False)
    namespace: Any = field(compare=False)


class InternalTimerService:
    """One instance per keyed operator (ref getInternalTimerService:782)."""

    def __init__(self, max_parallelism: int, triggerable=None):
        # triggerable: object with on_event_time(timer) / on_processing_time(timer)
        self.max_parallelism = max_parallelism
        self.triggerable = triggerable
        self._event_q: List[Tuple[int, int, InternalTimer]] = []
        self._proc_q: List[Tuple[int, int, InternalTimer]] = []
        self._event_set: Set[Tuple[int, Any, Any]] = set()
        self._proc_set: Set[Tuple[int, Any, Any]] = set()
        self._seq = 0
        self.current_watermark = -(2**62)
        self.current_processing_time = -(2**62)

    # -- registration (dedup exactly as the reference: set + queue) -------
    def register_event_time_timer(self, namespace, key, ts: int):
        k = (ts, key, namespace)
        if k in self._event_set:
            return
        self._event_set.add(k)
        self._seq += 1
        heapq.heappush(self._event_q, (ts, self._seq, InternalTimer(ts, key, namespace)))

    def register_processing_time_timer(self, namespace, key, ts: int):
        k = (ts, key, namespace)
        if k in self._proc_set:
            return
        self._proc_set.add(k)
        self._seq += 1
        heapq.heappush(self._proc_q, (ts, self._seq, InternalTimer(ts, key, namespace)))

    def delete_event_time_timer(self, namespace, key, ts: int):
        self._event_set.discard((ts, key, namespace))

    def delete_processing_time_timer(self, namespace, key, ts: int):
        self._proc_set.discard((ts, key, namespace))

    # -- advancement ------------------------------------------------------
    def advance_watermark(self, ts: int):
        """Fire all event-time timers <= ts (ref advanceWatermark:264)."""
        self.current_watermark = ts
        fired = []
        while self._event_q and self._event_q[0][0] <= ts:
            _, _, timer = heapq.heappop(self._event_q)
            k = (timer.timestamp, timer.key, timer.namespace)
            if k not in self._event_set:
                continue  # deleted
            self._event_set.discard(k)
            fired.append(timer)
            if self.triggerable is not None:
                self.triggerable.on_event_time(timer)
        return fired

    def advance_processing_time(self, ts: int):
        self.current_processing_time = ts
        fired = []
        while self._proc_q and self._proc_q[0][0] <= ts:
            _, _, timer = heapq.heappop(self._proc_q)
            k = (timer.timestamp, timer.key, timer.namespace)
            if k not in self._proc_set:
                continue
            self._proc_set.discard(k)
            fired.append(timer)
            if self.triggerable is not None:
                self.triggerable.on_processing_time(timer)
        return fired

    def drain(self, ts: int):
        """End-of-stream flush: advance both clocks to `ts` and fire each
        PRE-EXISTING timer exactly once. Timers that callbacks re-register
        during the drain (continuous triggers re-arming) are discarded
        instead of cascading — otherwise a trigger re-registering t+interval
        <= ts would fire ~2^62/interval times."""
        self.current_watermark = ts
        self.current_processing_time = ts
        limit = self._seq
        for q, live, cb in (
            (self._event_q, self._event_set,
             lambda t: self.triggerable.on_event_time(t)),
            (self._proc_q, self._proc_set,
             lambda t: self.triggerable.on_processing_time(t)),
        ):
            while q and q[0][0] <= ts:
                _, seq, timer = heapq.heappop(q)
                k = (timer.timestamp, timer.key, timer.namespace)
                if k not in live:
                    continue
                live.discard(k)
                if seq > limit:
                    continue  # registered during this drain: drop
                if self.triggerable is not None:
                    cb(timer)

    def next_processing_timer(self) -> Optional[int]:
        while self._proc_q:
            ts, _, timer = self._proc_q[0]
            if (timer.timestamp, timer.key, timer.namespace) in self._proc_set:
                return ts
            heapq.heappop(self._proc_q)
        return None

    # -- snapshot / restore by key group ----------------------------------
    def snapshot(self) -> Dict[int, list]:
        """-> {key_group: [(domain, ts, key, namespace), ...]}"""
        out: Dict[int, list] = {}
        for domain, live in (("event", self._event_set), ("proc", self._proc_set)):
            for ts, key, ns in live:
                kg = key_group_of(key, self.max_parallelism)
                out.setdefault(kg, []).append((domain, ts, key, ns))
        return out

    def restore(self, key_group_entries: Dict[int, list]):
        for entries in key_group_entries.values():
            for domain, ts, key, ns in entries:
                if domain == "event":
                    self.register_event_time_timer(ns, key, ts)
                else:
                    self.register_processing_time_timer(ns, key, ts)
