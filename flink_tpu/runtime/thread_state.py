"""Cross-thread shared-state registry (the ``thread-state`` lint's
annotation side; tools/lint/rules/thread_state.py).

The runtime runs four long-lived background threads next to the step
loop — ingest producer, checkpoint materializer, watchdog monitor, web
monitor handlers. Every attribute those threads MUTATE must either sit
lexically inside ``with self.<lock>:`` (auto-detected by the lint — no
entry needed here) or be registered below with a policy and a reason.
The registry is data, not code: the linter parses it as a literal and
never imports the runtime, and a reviewer reads it as the single
catalog of deliberately-unlocked cross-thread state.

Policies:

  ``single-writer:<thread>`` — only the named thread ever writes the
      attribute; readers tolerate staleness (GIL-atomic publication).
  ``locked-by-caller:<lock>`` — every call path into the mutating
      method holds the named lock; the lexical ``with`` lives in the
      caller, which the purely-lexical lint cannot see.

Adding an entry is a REVIEWED claim about the runtime's threading
contract — include the why, not just the policy.
"""

SHARED_STATE = {
    # Watchdog._trip runs on the monitor thread with _trip_lock HELD BY
    # ITS ONLY CALLER (_main's verify-pop-inject critical section); the
    # lexical `with` is one frame up, invisible to the lint.
    "Watchdog.trips":
        "locked-by-caller:_trip_lock — _main holds _trip_lock across "
        "the verify-pop-inject sequence that calls _trip",
    "Watchdog._tripping":
        "locked-by-caller:_trip_lock — same critical section as "
        "Watchdog.trips; disarm()'s cancel path takes the same lock",
}
