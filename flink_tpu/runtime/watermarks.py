"""Watermark generation strategies.

Role of the reference's AssignerWithPeriodicWatermarks /
BoundedOutOfOrdernessTimestampExtractor / AscendingTimestampExtractor
(SURVEY §2.5 "Event time / watermarks"), batch-adapted: the executor calls
`on_batch(max_ts_ms)` once per micro-batch (the batch boundary IS the
periodic emission point) and gets the current watermark in epoch ms.
"""

from __future__ import annotations

from dataclasses import dataclass

MIN_WATERMARK_MS = -(2**62)


@dataclass
class WatermarkStrategy:
    out_of_orderness_ms: int = 0
    idle_timeout_ms: int = 0  # reserved (multi-source idleness, later rounds)

    _current: int = MIN_WATERMARK_MS
    # newest event timestamp observed (telemetry: the event-time lag
    # gauge is max_seen - watermark, i.e. how far the watermark trails
    # the data it has already admitted — steady-state it equals the
    # out-of-orderness bound; growth means the watermark is stuck)
    _max_ts: int = MIN_WATERMARK_MS

    @staticmethod
    def for_monotonous_timestamps() -> "WatermarkStrategy":
        return WatermarkStrategy(0)

    @staticmethod
    def for_bounded_out_of_orderness(ms: int) -> "WatermarkStrategy":
        return WatermarkStrategy(ms)

    def on_batch(self, max_ts_ms) -> int:
        if max_ts_ms is not None:
            self._max_ts = max(self._max_ts, int(max_ts_ms))
            self._current = max(self._current, int(max_ts_ms) - self.out_of_orderness_ms - 1)
        return self._current

    def current(self) -> int:
        return self._current

    def max_event_ts(self) -> int:
        return self._max_ts

    def event_time_lag_ms(self):
        """max seen event time - watermark; None before any batch."""
        if self._max_ts == MIN_WATERMARK_MS or self._current == MIN_WATERMARK_MS:
            return None
        return self._max_ts - self._current

    def watermark_lag_ms(self, now_ms: int):
        """Wall clock - watermark (how far event time trails real time;
        only meaningful when event timestamps are epoch ms). None before
        the first watermark."""
        if self._current == MIN_WATERMARK_MS:
            return None
        return int(now_ms) - self._current
