"""Watchdog-supervised step loop: per-phase deadlines that convert a
distributed hang into a clean, attributed job failure.

The reference cancels a stuck Task via TaskCancelerWatchDog
(Task.java:1528: a watchdog thread that escalates a cancellation that
does not finish); a jax_graft step loop has the same exposure with
different phases — a wedged device fetch, a source that stops
producing, a materializer that never frees a staging slot. The
:class:`Watchdog` monitor thread checks one ARMED phase per supervised
thread; when a phase overruns its deadline it records the attribution
(phase name, elapsed, deadline), notifies ``on_trip`` (metrics), and
raises :class:`WatchdogError` inside the supervised thread via CPython's
async-exception hook, so the failure surfaces AT the stalled call with
the phase name in the message — the restart machinery then treats it
like any job failure (restore from the last checkpoint or die cleanly).

Delivery caveat (inherent to async exceptions): the error lands when the
blocked thread next executes Python bytecode. Every supervised wait in
this codebase is either a short-timeout loop (queue.get, Condition.wait,
sliced socket recv) or a device fetch; an OS-level block that never
returns cannot be interrupted from userspace — the watchdog still
records and reports the trip, which is the attribution half of the
contract.

Arming is two attribute stores + a monotonic read (< 1 us), so phases
can wrap every cycle of the hot loop; the monitor thread wakes every
``interval_s`` and does O(supervised threads) work.
"""

from __future__ import annotations

import ctypes
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional


class WatchdogError(RuntimeError):
    """A supervised phase overran its deadline. When raised via the
    async-exception hook CPython instantiates the class with no args —
    in the TARGET thread — so the monitor parks the attribution in
    ``pending_by_tid`` first and __init__ picks up its own thread's
    entry (per-tid: concurrent trips cannot swap messages)."""

    pending_by_tid: dict = {}

    def __init__(self, *args):
        if not args:
            msg = type(self).pending_by_tid.pop(
                threading.get_ident(), ""
            )
            if msg:
                args = (msg,)
        super().__init__(*args)


@dataclass
class WatchdogTrip:
    phase: str
    elapsed_s: float
    deadline_s: float
    thread_name: str
    detail: str = ""

    def message(self) -> str:
        base = (
            f"watchdog: phase {self.phase!r} exceeded its "
            f"{self.deadline_s:.1f}s deadline "
            f"({self.elapsed_s:.1f}s elapsed) on thread "
            f"{self.thread_name!r}"
        )
        return f"{base}: {self.detail}" if self.detail else base


class Watchdog:
    """deadlines: phase name -> seconds (entries <= 0 disable that
    phase). Phases nest: ``arm`` returns the previously armed slot and
    ``disarm(prev)`` restores it, so a checkpoint's slot wait can be
    attributed separately from the surrounding sync phase."""

    def __init__(self, deadlines: Dict[str, float],
                 interval_s: float = 1.0, name: str = "flink-tpu-watchdog",
                 on_trip: Optional[Callable[[WatchdogTrip], None]] = None):
        self.deadlines = {
            k: float(v) for k, v in deadlines.items() if v and v > 0
        }
        self.interval_s = max(0.05, float(interval_s))
        self.name = name
        self.on_trip = on_trip
        self.trips: List[WatchdogTrip] = []
        # tid -> (phase, t_armed, deadline_s, detail); plain dict ops are
        # GIL-atomic, which is all the monitor's snapshot read needs
        self._armed: Dict[int, tuple] = {}
        # tids with an injected-but-possibly-undelivered trip: disarm()
        # CANCELS the pending async exception when the supervised wait
        # completed in the monitor's observe->inject window, so a trip
        # can never detonate later in unrelated code. _trip_lock makes
        # the monitor's verify->pop->inject and disarm's cancel->restore
        # mutually exclusive — whichever wins, the loser sees a
        # consistent state (no injection after a completed disarm).
        self._tripping: set = set()
        self._trip_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # restore suspension (see suspend()): while > 0, newly armed
        # phases other than "restore" get a no-deadline slot — a
        # legitimately long cold restore must not trip the step-loop
        # deadlines tuned for steady-state phases
        self._suspended = 0

    @property
    def enabled(self) -> bool:
        return bool(self.deadlines)

    # -- supervised-thread side ----------------------------------------
    def arm(self, phase: str, detail: str = "", scale: float = 1.0):
        """Arm ``phase`` for the calling thread; returns the previous
        slot (restore it with ``disarm``). Unknown/disabled phases arm a
        no-deadline slot so nesting stays balanced. ``scale`` multiplies
        the configured deadline for phases whose legitimate duration is
        work-proportional — the resident drain arms ``device-drain``
        with scale = slots consumed, so one per-slot deadline covers
        every drain size without a deep drain tripping a shallow
        deadline. The sharded drain (pipeline.data-parallel) keeps that
        contract per shard: shards retire their slots concurrently, so
        the caller scales by slots alone on accelerator meshes and
        folds in n_shards only where the "chips" share host cores (the
        virtual CPU mesh), where concurrency is a fiction."""
        tid = threading.get_ident()
        prev = self._armed.get(tid)
        dl = self.deadlines.get(phase)
        if self._suspended and phase != "restore":
            dl = None   # restore in progress: only its own deadline runs
        if dl is None:
            self._armed[tid] = (phase, 0.0, 0.0, detail)
        else:
            dl = dl * max(1.0, float(scale))
            self._armed[tid] = (phase, time.monotonic(), dl, detail)
        return prev

    def suspend(self) -> None:
        """Disarm step-loop phase deadlines for the duration of a
        restore: nested arms (checkpoint drains, device fetches inside
        the restore) get no-deadline slots, so a long cold restore can
        only trip the dedicated ``restore`` phase
        (``watchdog.restore-timeout``), never a steady-state deadline
        misattributed mid-recovery. Counted, so nested restores (a
        restore retried inside the restart loop) balance."""
        self._suspended += 1

    def unsuspend(self) -> None:
        self._suspended = max(0, self._suspended - 1)

    def disarm(self, prev=None) -> None:
        tid = threading.get_ident()
        with self._trip_lock:
            if tid in self._tripping:
                # the phase finished between the monitor's overdue check
                # and the async delivery: cancel the in-flight exception
                # (a no-op if it was already delivered and is unwinding
                # through this very disarm — then it surfaces AT the
                # armed phase, which is the correct attribution)
                self._tripping.discard(tid)
                WatchdogError.pending_by_tid.pop(tid, None)
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(tid), None
                )
            if prev is None:
                self._armed.pop(tid, None)
            else:
                self._armed[tid] = prev

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "Watchdog":
        if self.enabled and self._thread is None:
            self._thread = threading.Thread(
                target=self._main, daemon=True, name=self.name
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # -- monitor side ---------------------------------------------------
    def _main(self) -> None:
        while not self._stop.wait(self.interval_s):
            now = time.monotonic()
            for tid, slot in list(self._armed.items()):
                phase, t0, dl, detail = slot
                if dl <= 0 or now - t0 <= dl:
                    continue
                with self._trip_lock:
                    # verify-pop-inject atomically vs disarm: a phase
                    # that completed (disarm ran) can never be tripped,
                    # and a trip decided here is cancellable by the
                    # very next disarm
                    if self._armed.get(tid) is not slot:
                        continue
                    self._armed.pop(tid, None)
                    self._trip(tid, phase, now - t0, dl, detail)

    def _trip(self, tid: int, phase: str, elapsed: float, deadline: float,
              detail: str) -> None:
        """Record + inject one trip. Caller holds _trip_lock."""
        tname = next(
            (t.name for t in threading.enumerate() if t.ident == tid),
            str(tid),
        )
        trip = WatchdogTrip(
            phase=phase, elapsed_s=elapsed, deadline_s=deadline,
            thread_name=tname, detail=detail,
        )
        self.trips.append(trip)
        del self.trips[:-50]
        if self.on_trip is not None:
            try:
                self.on_trip(trip)
            except Exception:
                pass          # observability must never kill the monitor
        WatchdogError.pending_by_tid[tid] = trip.message()
        self._tripping.add(tid)
        _async_raise(tid, WatchdogError)


def _async_raise(tid: int, exc_type) -> bool:
    """Raise ``exc_type`` inside thread ``tid`` at its next bytecode
    boundary (CPython's PyThreadState_SetAsyncExc). Returns False when
    the thread no longer exists."""
    res = ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(tid), ctypes.py_object(exc_type)
    )
    if res > 1:        # shouldn't happen: undo and refuse
        ctypes.pythonapi.PyThreadState_SetAsyncExc(
            ctypes.c_ulong(tid), None
        )
        return False
    return res == 1


def watchdog_from_config(config, on_trip=None) -> Optional[Watchdog]:
    """Build the step-loop watchdog from ``watchdog.*`` config (None when
    disabled). Phase deadlines in SECONDS; 0 disables one phase.
    Defaults are deliberately generous — the watchdog is a hang
    detector, not a latency SLO. Reads go through the declared
    ConfigOptions so conf-file strings coerce strictly (a misspelled
    boolean is an error, never a silently-disabled watchdog)."""
    from flink_tpu.core.config import CoreOptions as CO

    if config is None or not config.get(CO.WATCHDOG_ENABLED):
        return None
    deadlines = {
        # the ingest wait: 0 by default — a legitimate source may idle
        # indefinitely (sockets); enable for must-produce pipelines
        "source": config.get(CO.WATCHDOG_SOURCE_TIMEOUT),
        "fire": config.get(CO.WATCHDOG_FIRE_TIMEOUT),
        "barrier_fetch": config.get(CO.WATCHDOG_FETCH_TIMEOUT),
        "checkpoint_sync": config.get(CO.WATCHDOG_CKPT_SYNC_TIMEOUT),
        "materializer_slot": config.get(CO.WATCHDOG_SLOT_TIMEOUT),
        # PER-SLOT seconds: the resident drain arms this scaled by the
        # slot count it dispatched (Watchdog.arm scale=), so the
        # deadline tracks the work actually handed to the device
        "device-drain": config.get(CO.WATCHDOG_DRAIN_TIMEOUT),
        # recovery gets its OWN deadline; the step-loop phases above are
        # suspended while a restore runs (Watchdog.suspend)
        "restore": config.get(CO.WATCHDOG_RESTORE_TIMEOUT),
    }
    wd = Watchdog(
        deadlines, interval_s=config.get(CO.WATCHDOG_INTERVAL),
        on_trip=on_trip,
    )
    return wd if wd.enabled else None
