"""ExecutionGraph — per-vertex execution attempts + the job state machine
(ref flink-runtime executiongraph/ExecutionGraph.java,
ExecutionVertex.java, Execution.java, ExecutionState.java, JobStatus).

The reference tracks one Execution (attempt) per subtask with a strict
state machine (CREATED -> SCHEDULED -> DEPLOYING -> RUNNING -> terminal)
and a job-level JobStatus; restarts create NEW attempts rather than
mutating old ones, preserving failure history. The single-controller
SPMD runtime executes a job as one fused step-loop, so "deployment" is
compilation and a restart restores the whole pipeline — but the
OBSERVABLE model is kept: every logical operator of the stream graph
becomes an ExecutionJobVertex whose vertices advance through the same
states together, attempts accumulate across restarts with their failure
causes, and illegal transitions raise (the reference's
ConcurrentModification guard against state races).

Wired by MiniCluster: submission builds the graph from the job's
transformations; the executor's restart loop notifies it through the
environment's execution listener; the web monitor's /jobs/<id>/vertices
serves it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ref ExecutionState.java — the per-attempt machine
STATES = ("CREATED", "SCHEDULED", "DEPLOYING", "RUNNING", "FINISHED",
          "CANCELING", "CANCELED", "FAILED")
_LEGAL = {
    "CREATED": {"SCHEDULED", "CANCELED", "FAILED"},
    "SCHEDULED": {"DEPLOYING", "CANCELED", "FAILED"},
    "DEPLOYING": {"RUNNING", "CANCELING", "CANCELED", "FAILED"},
    "RUNNING": {"FINISHED", "CANCELING", "CANCELED", "FAILED"},
    "CANCELING": {"CANCELED", "FAILED"},
    "FINISHED": set(),
    "CANCELED": set(),
    "FAILED": set(),
}

# ref JobStatus — the job-level machine
JOB_STATES = ("CREATED", "RUNNING", "FAILING", "FAILED", "CANCELLING",
              "CANCELED", "FINISHED", "RESTARTING")
_JOB_LEGAL = {
    "CREATED": {"RUNNING", "FAILED", "CANCELED"},
    "RUNNING": {"FINISHED", "FAILING", "CANCELLING", "RESTARTING"},
    "FAILING": {"FAILED", "RESTARTING"},
    "RESTARTING": {"RUNNING", "FAILED", "CANCELED"},
    "CANCELLING": {"CANCELED"},
    "FINISHED": set(),
    "FAILED": set(),
    "CANCELED": set(),
}


class IllegalTransition(RuntimeError):
    pass


@dataclass
class ExecutionAttempt:
    """One Execution (ref Execution.java): attempt number + timestamped
    state history + failure cause."""

    attempt: int
    state: str = "CREATED"
    state_times: Dict[str, float] = field(default_factory=dict)
    failure_cause: Optional[str] = None

    def __post_init__(self):
        self.state_times.setdefault("CREATED", time.time())

    def transition(self, new: str, cause: Optional[str] = None):
        if new not in STATES:
            raise ValueError(f"unknown state {new!r}")
        if new not in _LEGAL[self.state]:
            raise IllegalTransition(
                f"attempt {self.attempt}: {self.state} -> {new} is illegal"
            )
        self.state = new
        self.state_times[new] = time.time()
        if cause is not None:
            self.failure_cause = cause


@dataclass
class ExecutionVertex:
    """One subtask of an operator (ref ExecutionVertex.java): the current
    attempt plus the full prior-attempt history."""

    task_name: str
    subtask_index: int
    attempts: List[ExecutionAttempt] = field(default_factory=list)

    def __post_init__(self):
        if not self.attempts:
            self.attempts.append(ExecutionAttempt(1))

    @property
    def current(self) -> ExecutionAttempt:
        return self.attempts[-1]

    def reset_for_restart(self):
        """ref ExecutionVertex.resetForNewExecution: a NEW attempt,
        history preserved."""
        self.attempts.append(ExecutionAttempt(len(self.attempts) + 1))


@dataclass
class ExecutionJobVertex:
    """One logical operator (ref ExecutionJobVertex.java)."""

    name: str
    kind: str
    parallelism: int
    inputs: List[int] = field(default_factory=list)   # upstream vertex ids
    vertices: List[ExecutionVertex] = field(default_factory=list)

    def __post_init__(self):
        if not self.vertices:
            self.vertices = [
                ExecutionVertex(self.name, i) for i in range(self.parallelism)
            ]


class ExecutionGraph:
    """Job-level graph + state machine (ref ExecutionGraph.java)."""

    def __init__(self, job_id: str, job_name: str):
        self.job_id = job_id
        self.job_name = job_name
        self.state = "CREATED"
        self.state_times: Dict[str, float] = {"CREATED": time.time()}
        self.job_vertices: Dict[int, ExecutionJobVertex] = {}
        self.restarts = 0
        self.failure_causes: List[str] = []

    # -- construction -----------------------------------------------------
    @staticmethod
    def from_transformations(job_id: str, job_name: str, sinks,
                             parallelism: int = 1) -> "ExecutionGraph":
        """Build from the stream graph reachable from the sink
        transformations (the JobGraph -> ExecutionGraph attach step)."""
        from flink_tpu.graph.stream_graph import parents_of, walk_dag

        eg = ExecutionGraph(job_id, job_name)
        for t in walk_dag(sinks):
            eg.job_vertices[t.id] = ExecutionJobVertex(
                name=t.name,
                kind=type(t).__name__.replace("Transformation", ""),
                parallelism=parallelism,
                inputs=[p.id for p in parents_of(t)],
            )
        return eg

    # -- job state machine ------------------------------------------------
    def transition_job(self, new: str):
        if new not in JOB_STATES:
            raise ValueError(f"unknown job state {new!r}")
        if new not in _JOB_LEGAL[self.state]:
            raise IllegalTransition(
                f"job {self.job_id}: {self.state} -> {new} is illegal"
            )
        self.state = new
        self.state_times[new] = time.time()

    def _all(self, fn):
        for jv in self.job_vertices.values():
            for v in jv.vertices:
                fn(v)

    def deploy_all(self):
        """CREATED -> SCHEDULED -> DEPLOYING -> RUNNING for every vertex
        (one fused pipeline: the whole graph deploys together)."""
        self.transition_job("RUNNING")
        for s in ("SCHEDULED", "DEPLOYING", "RUNNING"):
            self._all(lambda v, _s=s: v.current.transition(_s))

    def finish_all(self):
        self._all(lambda v: v.current.transition("FINISHED"))
        self.transition_job("FINISHED")

    def cancel_all(self):
        self.transition_job("CANCELLING")
        self._all(lambda v: v.current.transition("CANCELING"))
        self._all(lambda v: v.current.transition("CANCELED"))
        self.transition_job("CANCELED")

    def fail_all(self, cause: str, will_restart: bool):
        self.failure_causes.append(cause)
        self.transition_job("FAILING")
        self._all(lambda v: v.current.transition("FAILED", cause))
        if will_restart:
            # ref ExecutionGraph.restart: new attempts, history kept
            self.restarts += 1
            self.transition_job("RESTARTING")
            self._all(lambda v: v.reset_for_restart())
            self.deploy_all()
        else:
            self.transition_job("FAILED")

    # -- observability (web /jobs/<id>/vertices) --------------------------
    def vertices_summary(self) -> List[dict]:
        out = []
        for vid, jv in self.job_vertices.items():
            cur = [v.current for v in jv.vertices]
            out.append({
                "id": vid,
                "name": jv.name,
                "type": jv.kind,
                "parallelism": jv.parallelism,
                "inputs": jv.inputs,
                "status": cur[0].state if cur else "CREATED",
                "attempt": cur[0].attempt if cur else 0,
                "start-time": int(
                    min(a.state_times.get("CREATED", 0) for a in cur) * 1000
                ) if cur else -1,
            })
        return out

    def summary(self) -> dict:
        return {
            "jid": self.job_id,
            "state": self.state,
            "restarts": self.restarts,
            "failure-causes": self.failure_causes,
            "vertices": self.vertices_summary(),
        }
