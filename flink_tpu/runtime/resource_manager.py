"""ResourceManager — slot accounting + placement over the worker fleet.

The reference's FlinkResourceManager (flink-runtime/.../clusterframework/
FlinkResourceManager.java:95) sits between the JobManager and the cluster
framework: it tracks registered TaskManagers and their slots, satisfies
slot requests, and asks the framework (YARN/Mesos) for more containers
when the pool runs dry. TPU-native redesign: the resource unit is an
ACCELERATOR LEASE — one worker process owning a device (or a virtual-mesh
slice) for one job attempt — so a "slot" is a lease grant and scaling up
means launching another worker process (the per-job container pattern the
reference's YARN session uses, YarnFlinkResourceManager).

Pieces:
  * TaskManagerPool — registered executors with declared slot counts,
    allocation/release bookkeeping, pending-request queue (ref
    SlotManager in later reference versions; InstanceManager in 1.2).
  * ResourceManager — placement policy over the pool + an optional
    `launcher` callback standing in for the cluster framework: when a
    request cannot be satisfied it may start a new worker
    (ref FlinkResourceManager.requestNewWorkers).
  * ProcessClusterResourceManager — binds the pool to a live
    ProcessCluster: registration/death events feed the pool, placement
    drives ProcessCluster.submit onto a chosen worker's environment.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional


@dataclass
class TaskManagerInfo:
    tm_id: str
    slots: int
    allocated: int = 0
    tags: dict = field(default_factory=dict)   # e.g. {"host": ..., "devices": N}
    registered_at: float = field(default_factory=time.time)

    @property
    def free(self) -> int:
        return self.slots - self.allocated


@dataclass
class SlotRequest:
    request_id: str
    job_name: str
    slots: int = 1


@dataclass
class SlotGrant:
    request_id: str
    tm_id: str
    slots: int


class TaskManagerPool:
    """Slot bookkeeping (ref InstanceManager + slot availability)."""

    def __init__(self):
        self._tms: Dict[str, TaskManagerInfo] = {}
        self._lock = threading.Lock()

    def register(self, tm_id: str, slots: int, **tags):
        if slots < 1:
            raise ValueError("a TaskManager needs >= 1 slot")
        with self._lock:
            if tm_id in self._tms:
                # re-registration keeps existing allocations (the worker
                # proved liveness; its leases are still valid)
                self._tms[tm_id].slots = slots
                self._tms[tm_id].tags.update(tags)
            else:
                self._tms[tm_id] = TaskManagerInfo(tm_id, slots, tags=tags)

    def unregister(self, tm_id: str) -> Optional[TaskManagerInfo]:
        with self._lock:
            return self._tms.pop(tm_id, None)

    def allocate(self, slots: int = 1) -> Optional[str]:
        """Pick the TM with the most free slots (spread placement, the
        reference's default)."""
        with self._lock:
            best = None
            for tm in self._tms.values():
                if tm.free >= slots and (
                    best is None or tm.free > best.free
                ):
                    best = tm
            if best is None:
                return None
            best.allocated += slots
            return best.tm_id

    def release(self, tm_id: str, slots: int = 1):
        with self._lock:
            tm = self._tms.get(tm_id)
            if tm is not None:
                tm.allocated = max(0, tm.allocated - slots)

    def overview(self) -> List[dict]:
        with self._lock:
            return [
                {"id": tm.tm_id, "slots": tm.slots, "free": tm.free,
                 **tm.tags}
                for tm in self._tms.values()
            ]

    @property
    def total_free(self) -> int:
        with self._lock:
            return sum(tm.free for tm in self._tms.values())


class ResourceManager:
    """Placement + elastic scale-up (ref FlinkResourceManager.java:95).

    `launcher(n)` is the cluster-framework seam: called when a request
    cannot be satisfied, it should (asynchronously) bring up n more
    workers which then register — exactly requestNewWorkers' contract.
    Requests wait until a grant or timeout."""

    def __init__(self, pool: Optional[TaskManagerPool] = None,
                 launcher: Optional[Callable[[int], None]] = None):
        self.pool = pool or TaskManagerPool()
        self.launcher = launcher
        self._pending: List[tuple] = []   # (SlotRequest, event, box)
        self._lock = threading.Lock()
        self._grants: Dict[str, SlotGrant] = {}
        self.events: List[dict] = []

    def _event(self, kind: str, **kw):
        self.events.append({"event": kind, "t": time.time(), **kw})

    def notify_registered(self, tm_id: str, slots: int, **tags):
        self.pool.register(tm_id, slots, **tags)
        self._event("tm-registered", tm=tm_id, slots=slots)
        self._satisfy_pending()

    def notify_dead(self, tm_id: str):
        """DeathWatch feed: a dead TM's grants are void; jobs on it are
        the restart machinery's problem (ProcessCluster), the RM just
        reclaims the accounting."""
        info = self.pool.unregister(tm_id)
        if info is not None:
            self._event("tm-dead", tm=tm_id, lost_slots=info.slots)

    def request_slots(self, req: SlotRequest,
                      timeout_s: float = 30.0) -> SlotGrant:
        """Block until granted (or raise TimeoutError). Triggers the
        launcher when the pool cannot satisfy the request now.

        allocate-or-enqueue is ATOMIC under the RM lock — the same lock
        _satisfy_pending allocates under — so a release landing between
        a failed allocate and the enqueue cannot be lost (it either
        precedes the allocate and satisfies it, or follows the enqueue
        and finds the request pending)."""
        ev = threading.Event()
        box: dict = {}
        with self._lock:
            tm = self.pool.allocate(req.slots)
            if tm is not None:
                g = SlotGrant(req.request_id, tm, req.slots)
                self._grants[req.request_id] = g
            else:
                self._pending.append((req, ev, box))
        if tm is not None:
            self._event("granted", request=req.request_id, tm=tm)
            return SlotGrant(req.request_id, tm, req.slots)
        if self.launcher is not None:
            self._event("scale-up", want=req.slots)
            self.launcher(req.slots)
        if not ev.wait(timeout_s):
            with self._lock:
                self._pending = [
                    p for p in self._pending if p[1] is not ev
                ]
                # the grant may have landed in the race window
                if "grant" not in box:
                    raise TimeoutError(
                        f"no TaskManager could satisfy {req.slots} "
                        f"slot(s) within {timeout_s}s "
                        f"(pool free={self.pool.total_free})"
                    )
        return box["grant"]

    def release(self, request_id: str):
        g = self._grants.pop(request_id, None)
        if g is not None:
            self.pool.release(g.tm_id, g.slots)
            self._event("released", request=request_id, tm=g.tm_id)
            self._satisfy_pending()

    def _satisfy_pending(self):
        """Grant waiting requests. Allocation + pending-list mutation run
        atomically under the RM lock so concurrent triggers (a release
        racing a registration) cannot both allocate for one request."""
        granted = []
        with self._lock:
            remaining = []
            for req, ev, box in self._pending:
                tm = self.pool.allocate(req.slots)
                if tm is None:
                    remaining.append((req, ev, box))
                    continue
                g = SlotGrant(req.request_id, tm, req.slots)
                self._grants[req.request_id] = g
                box["grant"] = g
                granted.append((req, ev, tm))
            self._pending = remaining
        for req, ev, tm in granted:
            self._event("granted", request=req.request_id, tm=tm)
            ev.set()


class ProcessClusterResourceManager:
    """Admission control over a ProcessCluster's per-job worker
    containers (ref YarnFlinkResourceManager: the container IS the
    resource). One synthetic TaskManager per host models the machine's
    accelerator capacity — at most `capacity` concurrent job-workers
    hold a lease. submit_with_lease blocks for a free lease before
    spawning; a job's lease is released when its worker reaches a
    TERMINAL state (FINISHED/FAILED/gave-up) in the cluster's event log
    — a mid-job death-and-respawn keeps the lease, matching the
    reference's container retention across task restarts."""

    def __init__(self, cluster, capacity: int = 1,
                 host_id: str = "accelerator-pool"):
        self.cluster = cluster
        self.rm = ResourceManager()
        self.rm.notify_registered(host_id, capacity)
        self._seen_events = 0
        self._leases: Dict[str, str] = {}   # worker_id -> request_id
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watcher = threading.Thread(
            target=self._watch, daemon=True, name="resource-manager-watch"
        )
        self._watcher.start()

    def _watch(self):
        while not self._stop.wait(0.1):
            self.poll_events()

    def poll_events(self):
        events = self.cluster.events
        while self._seen_events < len(events):
            e = events[self._seen_events]
            self._seen_events += 1
            terminal = (
                e["event"] == "gave-up"
                or (e["event"] == "status"
                    and e.get("status") in ("FINISHED", "FAILED"))
            )
            if terminal:
                self._release_worker(e.get("worker"))

    def _release_worker(self, worker_id):
        with self._lock:
            req_id = self._leases.pop(worker_id, None)
        if req_id is not None:
            self.rm.release(req_id)

    def stop(self):
        self._stop.set()

    def submit_with_lease(self, builder_ref: str, job_name: str,
                          checkpoint_dir: str, timeout_s: float = 30.0,
                          extra_env: Optional[dict] = None) -> str:
        """Grant-then-place: the job only spawns once a lease is held, so
        the accelerator is never oversubscribed by concurrent submits."""
        req = SlotRequest(f"req-{job_name}-{time.time_ns()}", job_name)
        self.rm.request_slots(req, timeout_s=timeout_s)
        try:
            wid = self.cluster.submit(builder_ref, job_name,
                                      checkpoint_dir, extra_env=extra_env)
        except Exception:
            self.rm.release(req.request_id)
            raise
        with self._lock:
            self._leases[wid] = req.request_id
        return wid
