"""MiniCluster: in-process job management — submit/list/cancel/savepoint.

The role of the reference's LocalFlinkMiniCluster + JobManager job registry
(SURVEY §2.2/§3.1) for a single-controller TPU deployment: jobs run on
worker threads around their compiled SPMD step loops, the cluster tracks
status (the JobStatus state machine subset RUNNING/FINISHED/FAILED/
CANCELED), and control requests (cancel, savepoint) reach the executor
cooperatively at micro-batch boundaries — the same cadence at which the
reference's Task thread observes cancellation and barrier injection.

A JSON-over-TCP control server exposes the cluster to the CLI
(ref JobManager's Akka endpoints consumed by CliFrontend).
"""

from __future__ import annotations

import itertools
import json
import socket
import socketserver
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


class JobCancelledException(Exception):
    """Raised inside the executor loop when a cancel request is observed."""


class SavepointRequest:
    def __init__(self, path: str):
        self.path = path
        self._done = threading.Event()
        self.result: Optional[str] = None
        self.error: Optional[BaseException] = None

    def set_result(self, path: str):
        self.result = path
        self._done.set()

    def set_error(self, e: BaseException):
        self.error = e
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> str:
        if not self._done.wait(timeout):
            raise TimeoutError("savepoint did not complete in time")
        if self.error is not None:
            raise self.error
        return self.result


class JobControl:
    """Cooperative control channel polled by the executor each micro-batch
    (the reference's Task.cancelExecution + checkpoint trigger analog)."""

    def __init__(self):
        self.cancel_event = threading.Event()
        self._savepoint: Optional[SavepointRequest] = None
        self._lock = threading.Lock()

    def request_cancel(self):
        self.cancel_event.set()

    def request_savepoint(self, path: str) -> SavepointRequest:
        req = SavepointRequest(path)
        with self._lock:
            if self._savepoint is not None and not self._savepoint._done.is_set():
                raise RuntimeError("a savepoint is already in progress")
            self._savepoint = req
        return req

    def take_savepoint_request(self) -> Optional[SavepointRequest]:
        with self._lock:
            req, self._savepoint = self._savepoint, None
            return req

    def remove_request(self, req: SavepointRequest) -> bool:
        """Detach `req` only if it is still the pending one — a caller must
        never pop another caller's request."""
        with self._lock:
            if self._savepoint is req:
                self._savepoint = None
                return True
            return False


@dataclass
class JobRecord:
    job_id: str
    name: str
    env: Any
    control: JobControl
    thread: threading.Thread = None
    status: str = "CREATED"     # CREATED|RUNNING|FINISHED|FAILED|CANCELED
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    error: Optional[str] = None
    handle: Any = None
    execution_graph: Any = None   # runtime/execution_graph.ExecutionGraph

    def summary(self) -> Dict[str, Any]:
        out = {
            "jid": self.job_id,
            "name": self.name,
            "state": self.status,
            "start-time": int(self.start_time * 1000),
            "end-time": int(self.end_time * 1000) if self.end_time else -1,
            "duration": int(
                ((self.end_time or time.time()) - self.start_time) * 1000
            ),
        }
        if self.error:
            out["error"] = self.error
        return out


class MiniCluster:
    _ids = itertools.count(1)

    def __init__(self):
        self.jobs: Dict[str, JobRecord] = {}
        self._lock = threading.Lock()
        self._server: Optional[socketserver.TCPServer] = None

    # -- job lifecycle ---------------------------------------------------
    def submit(self, env, job_name: str = "job",
               restore_from: Optional[str] = None) -> str:
        if getattr(env, "_control", None) is not None:
            raise RuntimeError(
                "this environment already has a cluster-submitted job; "
                "use one StreamExecutionEnvironment per submission"
            )
        job_id = f"job-{next(self._ids):04d}"
        control = JobControl()
        env._control = control
        rec = JobRecord(job_id, job_name, env, control)
        # attach the ExecutionGraph: per-vertex attempts + the job state
        # machine (ref JobGraph -> ExecutionGraph.attachJobGraph)
        from flink_tpu.runtime.execution_graph import ExecutionGraph

        eg = ExecutionGraph.from_transformations(
            job_id, job_name, getattr(env, "_sinks", []),
            parallelism=getattr(env, "parallelism", 1),
        )
        rec.execution_graph = eg

        def on_execution_event(kind, cause="restart"):
            if kind == "restart":
                # the executor only notifies when it IS restarting, so
                # the graph always cycles to new attempts here; the real
                # exception rides in as the failure cause
                eg.fail_all(cause, will_restart=True)

        env._execution_listener = on_execution_event

        def run():
            rec.status = "RUNNING"
            eg.deploy_all()
            try:
                rec.handle = env.execute(job_name, restore_from=restore_from)
                rec.status = "FINISHED"
                eg.finish_all()
            except JobCancelledException:
                rec.status = "CANCELED"
                eg.cancel_all()
            except Exception as e:
                rec.status = "FAILED"
                rec.error = "".join(
                    traceback.format_exception_only(type(e), e)
                ).strip()
                eg.fail_all(rec.error, will_restart=False)
            finally:
                rec.end_time = time.time()
                env._control = None
                # the graph is terminal: a later direct env.execute() of
                # a reused environment must not mutate this job's history
                env._execution_listener = None
                # a savepoint request the loop never observed must fail
                # promptly, not time out its waiter
                req = control.take_savepoint_request()
                if req is not None:
                    req.set_error(RuntimeError(
                        f"job {job_id} ended ({rec.status}) before the "
                        f"savepoint could be taken"
                    ))

        rec.thread = threading.Thread(target=run, daemon=True,
                                      name=f"minicluster-{job_id}")
        with self._lock:
            self.jobs[job_id] = rec
        rec.thread.start()
        return job_id

    def _rec(self, job_id: str) -> JobRecord:
        rec = self.jobs.get(job_id)
        if rec is None:
            raise KeyError(f"unknown job {job_id!r}")
        return rec

    def cancel(self, job_id: str):
        self._rec(job_id).control.request_cancel()

    def stop(self, job_id: str):
        # ref stop-vs-cancel: stop asks sources to end gracefully; the
        # micro-batch loop treats both as a boundary-observed request
        self.cancel(job_id)

    def trigger_savepoint(self, job_id: str, path: str,
                          timeout_s: float = 120.0) -> str:
        rec = self._rec(job_id)
        if rec.status != "RUNNING":
            raise RuntimeError(f"job {job_id} is {rec.status}, not RUNNING")
        req = rec.control.request_savepoint(path)
        # the job may have finished between the status check and the
        # request attach, in which case its end-of-run drain already ran
        # and nothing will ever observe this request — fail it ourselves
        # (remove_request never pops a different caller's request)
        if rec.status != "RUNNING" and rec.control.remove_request(req):
            req.set_error(RuntimeError(
                f"job {job_id} ended ({rec.status}) before the "
                f"savepoint could be taken"
            ))
        return req.wait(timeout_s)

    def wait(self, job_id: str, timeout_s: Optional[float] = None) -> str:
        rec = self._rec(job_id)
        rec.thread.join(timeout_s)
        return rec.status

    def list_jobs(self):
        with self._lock:
            return [rec.summary() for rec in self.jobs.values()]

    @property
    def _METRIC_FIELDS(self):
        from flink_tpu.runtime.executor import JobMetrics

        return JobMetrics.GAUGE_FIELDS

    def job_detail(self, job_id: str) -> Dict[str, Any]:
        rec = self._rec(job_id)
        out = rec.summary()
        snap = rec.env.metric_registry.snapshot(f"jobs.{rec.name}.")
        out["metric-snapshot"] = snap
        # live gauges read the running JobMetrics; fall back to the finished
        # handle for jobs executed before metrics wiring
        metrics = {
            k.rsplit(".", 1)[-1]: v for k, v in snap.items()
            if k.rsplit(".", 1)[-1] in self._METRIC_FIELDS
        }
        if not metrics and rec.handle is not None:
            metrics = {
                k: getattr(rec.handle.metrics, k) for k in self._METRIC_FIELDS
            }
        if metrics:
            out["metrics"] = metrics
        # non-numeric engine tag (gauges carry only scalars): which CEP
        # engine ran — "device" count-NFA kernel or "host" NFA fallback
        live = getattr(rec.env, "_live_metrics", None)
        src = live or (rec.handle.metrics if rec.handle else None)
        if src is not None and getattr(src, "cep_engine", ""):
            out["cep-engine"] = src.cep_engine
        return out

    # -- control server (CliFrontend <-> JobManager channel) -------------
    def start_control_server(self, host: str = "127.0.0.1",
                             port: int = 0, config=None) -> int:
        """`config` (a core.config.Configuration) lets the operator set
        security.auth.token[-file] explicitly; otherwise the environment
        variables resolve (runtime/security.get_token)."""
        from flink_tpu.runtime import security

        cluster = self
        token = security.get_token(config)

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                line = self.rfile.readline()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    security.check(token, req)
                    resp = cluster._dispatch(req)
                except Exception as e:
                    resp = {"ok": False, "error": str(e)}
                self.wfile.write(
                    (json.dumps(resp, default=str) + "\n").encode()
                )

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        t = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="minicluster-control",
        )
        t.start()
        return self._server.server_address[1]

    def stop_control_server(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def _dispatch(self, req: Dict[str, Any]) -> Dict[str, Any]:
        action = req.get("action")
        if action == "list":
            return {"ok": True, "jobs": self.list_jobs()}
        if action == "info":
            return {"ok": True, "job": self.job_detail(req["job_id"])}
        if action in ("cancel", "stop"):
            getattr(self, action)(req["job_id"])
            return {"ok": True}
        if action == "savepoint":
            path = self.trigger_savepoint(req["job_id"], req["path"])
            return {"ok": True, "savepoint": path}
        raise ValueError(f"unknown action {action!r}")


def control_request(host: str, port: int, req: Dict[str, Any],
                    timeout_s: float = 130.0) -> Dict[str, Any]:
    """Client side of the control protocol (used by the CLI). Attaches
    the shared auth token when one is configured in the environment
    (runtime/security.py — SecurityContext.java:53 analog)."""
    from flink_tpu.runtime import security

    req = security.attach(req, security.get_token())
    with socket.create_connection((host, port), timeout=timeout_s) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf)
