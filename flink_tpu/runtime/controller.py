"""Self-tuning runtime: the closed loop over the telemetry planes.

PR 14 gave the runtime eyes (duty-cycle, ring-starved, occupancy, fire
latency), PR 17's pipeline doctor turned them into ranked findings with
config remedies, and PR 8's ``_rescale_live`` proved a savepoint-cut
rescale works without restart — this module is the part that *acts*
(ROADMAP item 3; Enthuse, arXiv:2405.18168, is the exemplar for
aggregation engines that adapt their configuration to the observed
workload). A :class:`RuntimeController` is serviced at the poll-cycle
boundary (the same seam the :class:`~flink_tpu.runtime.elastic.
ElasticityController` scale-up latch uses) and applies remedies LIVE
through two actuator classes:

* **config auto-tuning** — a bounded hill-climb over the declared hot
  knobs (drain fill target, megastep grouping, drain-stats cadence,
  tier prefetch horizon), keyed on the doctor's ranked findings with
  the raw device-saturated vs ring-starved regime as the fallback.
  Every move is ledgered with before/after evidence and put on
  probation: if the tracked metric (events/s) worsens past
  ``controller.revert-threshold`` within ``controller.probation-cycles``
  the move auto-reverts and that (knob, direction) sits out
  ``controller.cooldown-cycles``.
* **live hot-key-group rebalancing** — when the per-shard heat skew
  crosses ``controller.rebalance-threshold`` (or the doctor's
  kg-heat-skew finding asks for it), a heat-balanced contiguous
  re-slicing of the shard ranges (greedy prefix partition over the
  PR 17 per-group EWMA heat series) is applied through the executor's
  savepoint-cut ``_rescale_live`` machinery — tiers re-slice, the
  incremental chain re-bases, exactly-once preserved. Rate-limited by
  ``controller.min-rebalance-interval`` and gated off when the
  predicted imbalance gain is under ``controller.min-gain``.

Everything here is host-side arithmetic over already-fetched telemetry
(this module is on the hot-path-sync lint list): the actuators are
attribute/holder writes — data the compiled kernels already consume —
so a knob move never recompiles and never adds a dispatch, and with
``controller.enabled: off`` (the default) nothing here is constructed
at all. ``docs/self-tuning.md`` carries the catalog and the safety
argument.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

# Every actuator name the controller may ever register. The doctor's
# machine-actionable `action` descriptors must name one of these — the
# doctor->controller contract lint (tests/test_doctor.py) pins it, so
# remedies can't drift from what the controller can apply.
ACTUATOR_NAMES = (
    "ring-fill-target",     # effective drain fill target (fused.k)
    "dispatch-group",       # megastep grouping (steps-per-dispatch)
    "drain-stats-cadence",  # observability.drain-stats-every holder
    "tier-prefetch-ahead",  # state.tiers.prefetch-ahead-panes
    "rebalance-key-groups",  # the live heat-balanced re-slice
)


@dataclass
class Actuator:
    """One live-settable knob: ``get``/``set`` are host closures over
    executor state (an attribute or one-element-list holder write — no
    recompile, no dispatch), bounded to [lo, hi]. ``step`` picks the
    hill-climb stride: geometric (halve/double — fill targets and
    cadences span orders of magnitude) or additive (+-1 — small
    horizons like prefetch-ahead-panes)."""

    name: str
    get: Callable[[], int]
    set: Callable[[int], None]
    lo: int
    hi: int
    step: str = "geometric"

    def move(self, direction: str) -> Tuple[int, int]:
        """(current, clamped next) for one step in ``direction``."""
        cur = int(self.get())
        if self.step == "additive":
            nxt = cur + 1 if direction == "up" else cur - 1
        else:
            nxt = cur * 2 if direction == "up" else cur // 2
        return cur, max(self.lo, min(self.hi, int(nxt)))


# ---------------------------------------------------------- partitioning


def plan_balanced_slices(heat, n_shards: int):
    """Greedy prefix partition of the per-group heat series into
    ``n_shards`` contiguous, non-empty slices covering every group.

    Returns ``(starts, ends)`` as int lists with INCLUSIVE ends,
    strictly increasing — the same contract ``MeshContext.kg_bounds``
    serves (ingest routing searchsorteds over the ends). Zero-heat
    tails get a uniform epsilon so idle groups still spread instead of
    all landing on the last shard."""
    w = np.maximum(np.multiply(heat, 1.0), 0.0)
    maxp = int(w.shape[0])
    if n_shards < 1 or maxp < n_shards:
        raise ValueError(
            f"cannot slice {maxp} key-groups into {n_shards} shards")
    total = float(w.sum())
    # epsilon floor: groups the heat plane has never seen still need an
    # owner, and a fully-cold plane should fall back to uniform slices
    eps = max(total, 1.0) / (1000.0 * maxp)
    w = w + eps
    total = float(w.sum())
    cum = np.cumsum(w)
    starts: List[int] = []
    ends: List[int] = []
    lo = 0
    for s in range(n_shards):
        if s == n_shards - 1:
            hi = maxp - 1
        else:
            target = total * (s + 1) / n_shards
            hi = int(np.searchsorted(cum, target, side="left"))
            # closest prefix boundary, not first-crossing: when the
            # previous boundary sits a hair under the target,
            # overshooting by a whole group is strictly worse for the
            # max-shard-heat objective (and float ties on uniform heat
            # would otherwise break rightward into uneven slices)
            if hi >= maxp:
                hi = maxp - 1
            elif hi > lo and (abs(float(cum[hi - 1]) - target)
                              <= abs(float(cum[hi]) - target)):
                hi -= 1
            # each remaining shard keeps at least one group
            hi = max(lo, min(hi, maxp - 1 - (n_shards - 1 - s)))
        starts.append(lo)
        ends.append(hi)
        lo = hi + 1
    return starts, ends


def shard_heats(heat, starts, ends) -> List[float]:
    """Per-shard heat totals under contiguous inclusive ranges."""
    w = np.maximum(np.multiply(heat, 1.0), 0.0)
    return [
        float(w[int(starts[s]):int(ends[s]) + 1].sum())
        for s in range(len(starts))
    ]


def predicted_gain(heat, cur_starts, cur_ends, new_starts,
                   new_ends) -> float:
    """Hottest-shard heat now / hottest-shard heat after the re-slice —
    the imbalance improvement a rebalance is predicted to buy (1.0 =
    no improvement)."""
    cur = shard_heats(heat, cur_starts, cur_ends)
    new = shard_heats(heat, new_starts, new_ends)
    hot_new = max(new) if new else 0.0
    if hot_new <= 0.0:
        return 1.0
    return (max(cur) if cur else 0.0) / hot_new


# ------------------------------------------------------------ controller


class RuntimeController:
    """Closed-loop policy for one windowed job.

    The executor services it once per poll cycle (``service``); every
    ``interval_cycles``-th cycle it makes at most ONE decision — a knob
    move (with probation) or a rebalance (rate-limited, gain-gated).
    Web threads read :meth:`report` (served at
    ``/jobs/<jid>/controller``), so the ledger and counters sit behind
    a lock like the elasticity controller's.

    ``sensor`` returns the raw planes as one host dict:
    ``records`` (cumulative events in), ``duty``/``starved`` (the
    regime EWMAs, or None), ``heat`` (the per-group EWMA series, or
    None), ``kg_starts``/``kg_ends`` (the current inclusive shard
    ranges). ``findings_fn`` returns the doctor's ranked findings
    (machine-actionable ``action`` descriptors are consumed here).
    ``rebalancer(starts, ends)`` applies the savepoint-cut re-slice
    LIVE and raises on failure — the failure is ledgered before it
    propagates (the executor re-latches the pre-rebalance plan)."""

    def __init__(self, actuators: Dict[str, Actuator],
                 sensor: Callable[[], dict],
                 findings_fn: Optional[Callable[[], list]] = None,
                 rebalancer: Optional[Callable] = None, *,
                 interval_cycles: int = 16,
                 revert_threshold: float = 0.05,
                 probation_cycles: int = 16,
                 cooldown_cycles: int = 64,
                 rebalance_threshold: float = 4.0,
                 min_rebalance_interval: float = 30.0,
                 min_gain: float = 1.2,
                 persist_dir: Optional[str] = None,
                 clock: Callable[[], float] = time.monotonic):
        unknown = [n for n in actuators if n not in ACTUATOR_NAMES]
        if unknown:
            raise ValueError(
                f"unregistered controller actuator(s): {unknown} "
                f"(known: {list(ACTUATOR_NAMES)})")
        self.actuators = dict(actuators)
        self.sensor = sensor
        self.findings_fn = findings_fn
        self.rebalancer = rebalancer
        self.interval_cycles = max(1, int(interval_cycles))
        self.revert_threshold = float(revert_threshold)
        self.probation_cycles = max(1, int(probation_cycles))
        self.cooldown_cycles = max(0, int(cooldown_cycles))
        self.rebalance_threshold = float(rebalance_threshold)
        self.min_rebalance_interval = float(min_rebalance_interval)
        self.min_gain = float(min_gain)
        self.clock = clock
        self._lock = threading.Lock()
        self._cycle = 0
        self._seq = 0
        # trailing decision-point sample: (records, t) — the "before"
        # rate of the next move is measured against it
        self._last_records: Optional[int] = None
        self._last_t: Optional[float] = None
        self._probation: Optional[dict] = None
        self._cooldowns: Dict[Tuple[str, str], int] = {}
        self._last_rebalance_t: Optional[float] = None
        self._last_skip_sig: Optional[tuple] = None
        self._ledger: List[dict] = []
        # counters surfaced as Prometheus gauges
        self.actions = 0
        self.reverts = 0
        self.rebalances = 0
        self.rebalance_skips = 0
        self.rebalance_failures = 0
        # durable decision ledger (ISSUE 20 satellite): every entry is
        # also appended to <persist_dir>/controller-ledger.jsonl, and a
        # restarted job reloads prior runs' tail so /jobs/<jid>/
        # controller serves the MERGED history — "why is the knob at
        # this value" survives the restart that applied it
        self._run = 1
        self._history: List[dict] = []
        self._ledger_path = None
        self.persist_errors = 0
        if persist_dir:
            self._ledger_path = os.path.join(
                persist_dir, "controller-ledger.jsonl")
            self._load_history()

    # -- ledger ----------------------------------------------------------

    def _load_history(self):
        try:
            with open(self._ledger_path) as f:
                lines = f.readlines()
        except OSError:
            return
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue   # torn tail line from a crash mid-append
            if isinstance(e, dict):
                self._history.append(e)
        del self._history[:-400]
        if self._history:
            # continue the sequence across restarts: merged entries stay
            # totally ordered, and the run counter marks each restart
            self._seq = max(int(e.get("seq", 0)) for e in self._history)
            self._run = 1 + max(
                int(e.get("run", 1)) for e in self._history)

    @staticmethod
    def _jsonable(o):
        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        if isinstance(o, np.ndarray):
            return o.tolist()
        return str(o)

    def _log(self, kind: str, **fields) -> dict:
        self._seq += 1
        entry = {"seq": self._seq, "run": self._run,
                 "cycle": self._cycle,
                 "t_wall": round(time.time(), 3), "kind": kind}
        entry.update(fields)
        with self._lock:
            self._ledger.append(entry)
            del self._ledger[:-100]
        if self._ledger_path:
            try:
                with open(self._ledger_path, "a") as f:
                    f.write(json.dumps(entry, default=self._jsonable)
                            + "\n")
            except OSError:
                # observability must not kill the job; the counter (a
                # Prometheus gauge via report()) keeps the loss visible
                self.persist_errors += 1
        return entry

    # -- the loop --------------------------------------------------------

    def service(self):
        """One poll cycle. Cheap no-op except every
        ``interval_cycles``-th call."""
        self._cycle += 1
        if self._cycle % self.interval_cycles:
            return
        s = self.sensor() or {}
        now = self.clock()
        records = s.get("records")
        records = None if records is None else int(records)

        if self._probation is not None:
            self._maybe_close_probation(records, now)
            # no new move while a probe is open: its metric window must
            # not be polluted by a second actuation
            if self._probation is not None:
                return
        did = self._maybe_rebalance(s, now)
        if not did:
            self._maybe_tune(s, records, now)
        self._last_records, self._last_t = records, now

    # -- probation -------------------------------------------------------

    def _rate(self, rec0, t0, rec1, t1) -> Optional[float]:
        if rec0 is None or rec1 is None or t1 is None or t0 is None:
            return None
        dt = t1 - t0
        if dt <= 0 or rec1 < rec0:
            return None
        return (rec1 - rec0) / dt

    def _maybe_close_probation(self, records, now):
        prob = self._probation
        if self._cycle - prob["cycle"] < self.probation_cycles:
            return
        act = self.actuators.get(prob["actuator"])
        rate_after = self._rate(prob["records"], prob["t"], records, now)
        before = prob.get("rate_before")
        worsened = (
            rate_after is not None and before is not None and before > 0
            and rate_after < before * (1.0 - self.revert_threshold)
        )
        if worsened and act is not None:
            act.set(prob["before"])
            self.reverts += 1
            self._cooldowns[(prob["actuator"], prob["direction"])] = \
                self._cycle
            self._log(
                "revert", actuator=prob["actuator"],
                direction=prob["direction"], value=prob["before"],
                reverted_value=prob["after"], evidence={
                    "rate_before": before, "rate_after": rate_after,
                    "revert_threshold": self.revert_threshold,
                    "probed_move_seq": prob["seq"],
                })
        else:
            self._log(
                "probation-pass", actuator=prob["actuator"],
                direction=prob["direction"], value=prob["after"],
                evidence={"rate_before": before,
                          "rate_after": rate_after,
                          "probed_move_seq": prob["seq"]})
        self._probation = None

    def _cooled_down(self, name: str, direction: str) -> bool:
        at = self._cooldowns.get((name, direction))
        return (at is not None
                and self._cycle - at < self.cooldown_cycles)

    # -- rebalance arm ---------------------------------------------------

    def _maybe_rebalance(self, s: dict, now: float) -> bool:
        heat = s.get("heat")
        cur_starts, cur_ends = s.get("kg_starts"), s.get("kg_ends")
        if (self.rebalancer is None or heat is None
                or cur_ends is None or len(cur_ends) < 2):
            return False
        cur_sh = shard_heats(heat, cur_starts, cur_ends)
        mean = sum(cur_sh) / len(cur_sh)
        skew = (max(cur_sh) / mean) if mean > 0 else 0.0
        asked = any(
            (f.get("action") or {}).get("actuator")
            == "rebalance-key-groups"
            for f in self._findings()
        )
        if not asked and skew < self.rebalance_threshold:
            return False
        if (self._last_rebalance_t is not None
                and now - self._last_rebalance_t
                < self.min_rebalance_interval):
            return False
        starts, ends = plan_balanced_slices(heat, len(cur_ends))
        same = (len(ends) == len(cur_ends) and all(
            int(ends[i]) == int(cur_ends[i]) for i in range(len(ends))))
        gain = predicted_gain(heat, cur_starts, cur_ends, starts, ends)
        if same or gain < self.min_gain:
            sig = ("skip", tuple(ends), round(gain, 3))
            if sig != self._last_skip_sig:
                self._last_skip_sig = sig
                self.rebalance_skips += 1
                self._log("rebalance-skip", evidence={
                    "shard_skew": round(skew, 3),
                    "predicted_gain": round(gain, 3),
                    "min_gain": self.min_gain,
                    "unchanged_slices": same,
                })
            return False
        self._last_skip_sig = None
        self._last_rebalance_t = now
        entry_ev = {
            "shard_skew": round(skew, 3),
            "predicted_gain": round(gain, 3),
            "shard_heats_before": [round(h, 3) for h in cur_sh],
            "shard_heats_after": [
                round(h, 3) for h in shard_heats(heat, starts, ends)],
            "ends_before": [int(e) for e in cur_ends],
            "ends_after": [int(e) for e in ends],
        }
        try:
            self.rebalancer(starts, ends)
        except BaseException:
            # ledger the failure BEFORE it propagates: the executor
            # re-latches the pre-rebalance plan and takes recovery
            self.rebalance_failures += 1
            self._log("rebalance-failed", evidence=entry_ev)
            raise
        self.rebalances += 1
        self._log("rebalance", evidence=entry_ev)
        return True

    # -- tuning arm ------------------------------------------------------

    def _findings(self) -> list:
        if self.findings_fn is None:
            return []
        try:
            return list(self.findings_fn() or [])
        except Exception:
            return []

    def _pick_move(self, s: dict):
        """(actuator-name, direction, why) of the top-ranked applicable
        action — doctor findings first, raw regime as the fallback."""
        for f in self._findings():
            a = f.get("action") or {}
            name, direction = a.get("actuator"), a.get("direction")
            if (name in self.actuators and direction in ("up", "down")
                    and not self._cooled_down(name, direction)):
                return name, direction, f.get("rule", "finding")
        starved, duty = s.get("starved"), s.get("duty")
        if (starved is not None and starved > 0.5
                and "ring-fill-target" in self.actuators
                and not self._cooled_down("ring-fill-target", "down")):
            return "ring-fill-target", "down", "regime:ring-starved"
        if (duty is not None and duty > 0.9
                and "ring-fill-target" in self.actuators
                and not self._cooled_down("ring-fill-target", "up")):
            return "ring-fill-target", "up", "regime:device-saturated"
        return None

    def _maybe_tune(self, s: dict, records, now):
        pick = self._pick_move(s)
        if pick is None:
            return
        name, direction, why = pick
        act = self.actuators[name]
        cur, nxt = act.move(direction)
        if nxt == cur:
            return                      # already at the bound
        rate_before = self._rate(
            self._last_records, self._last_t, records, now)
        act.set(nxt)
        self.actions += 1
        entry = self._log(
            "tune", actuator=name, direction=direction, before=cur,
            after=nxt, evidence={
                "why": why, "rate_before": rate_before,
                "duty": s.get("duty"), "starved": s.get("starved"),
            })
        self._probation = {
            "seq": entry["seq"], "cycle": self._cycle,
            "actuator": name, "direction": direction, "before": cur,
            "after": nxt, "records": records, "t": now,
            "rate_before": rate_before,
        }

    # -- observability ---------------------------------------------------

    def report(self) -> dict:
        with self._lock:
            # merged history: prior runs' persisted tail + this run's
            # live entries, one totally-ordered list (seq continues
            # across restarts, each entry stamped with its run)
            ledger = self._history + list(self._ledger)
        knobs = {
            n: {"value": int(a.get()), "lo": a.lo, "hi": a.hi,
                "step": a.step}
            for n, a in self.actuators.items()
        }
        prob = self._probation
        return {
            "available": True,
            "cycle": self._cycle,
            "run": self._run,
            "restored_entries": len(self._history),
            "persist_errors": self.persist_errors,
            "interval_cycles": self.interval_cycles,
            "actions": self.actions,
            "reverts": self.reverts,
            "rebalances": self.rebalances,
            "rebalance_skips": self.rebalance_skips,
            "rebalance_failures": self.rebalance_failures,
            "probation": (
                None if prob is None else {
                    k: prob[k] for k in (
                        "actuator", "direction", "before", "after",
                        "cycle")
                }),
            "cooldowns": [
                {"actuator": n, "direction": d, "cycle": c}
                for (n, d), c in self._cooldowns.items()
            ],
            "actuators": knobs,
            "ledger": ledger,
        }
