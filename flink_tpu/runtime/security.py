"""Control-plane authentication — the SecurityContext analog.

The reference's SecurityContext (flink-runtime/.../security/
SecurityContext.java:53) installs Kerberos/JAAS credentials around
cluster communication. The TPU-native control plane is JSON-over-TCP
(runtime/cluster.py line protocol), so its security model is a shared
secret on every request:

  * the operator sets ``FLINK_TPU_AUTH_TOKEN`` (or points
    ``FLINK_TPU_AUTH_TOKEN_FILE`` at a secret file, the k8s-secret
    pattern) on controller AND clients/workers;
  * every control request carries ``auth: <token>``;
  * a token-configured server rejects requests whose token mismatches
    (constant-time compare), BEFORE dispatch — an unauthenticated caller
    cannot submit, cancel, or register.

Worker subprocesses inherit the controller's environment, so spawned
TaskManagers authenticate automatically; externally launched workers
must carry the same secret (exactly the reference's shared-keytab
deployment story).
"""

from __future__ import annotations

import hmac
import os
from typing import Optional

ENV_TOKEN = "FLINK_TPU_AUTH_TOKEN"
ENV_TOKEN_FILE = "FLINK_TPU_AUTH_TOKEN_FILE"


def get_token(config=None) -> Optional[str]:
    """Resolve the shared secret: explicit config key
    (``security.auth.token`` / ``security.auth.token-file``) wins over
    the environment; None = auth disabled (open cluster, the default —
    like the reference without a configured SecurityContext)."""
    if config is not None:
        tok = config.get_str("security.auth.token", "")
        if tok:
            return tok
        path = config.get_str("security.auth.token-file", "")
        if path:
            with open(path) as f:
                return f.read().strip()
    tok = os.environ.get(ENV_TOKEN)
    if tok:
        return tok
    path = os.environ.get(ENV_TOKEN_FILE)
    if path:
        with open(path) as f:
            return f.read().strip()
    return None


def check(expected: Optional[str], req: dict) -> None:
    """Server-side gate: raises PermissionError unless the request's
    ``auth`` matches the configured token (no-op when auth is off)."""
    if expected is None:
        return
    got = req.get("auth")
    if not isinstance(got, str) or not hmac.compare_digest(got, expected):
        raise PermissionError("control request rejected: bad auth token")


def attach(req: dict, token: Optional[str]) -> dict:
    if token is not None:
        req = dict(req)
        req["auth"] = token
    return req
